"""TCP full-mesh backend: the Gloo-equivalent control+data plane.

Workers rendezvous through the HTTP KV store (each PUTs its listening
address, then connects to every lower rank — the same connectFullMesh
bootstrap gloo performs against the KV store, ref: horovod/common/gloo/
gloo_context.cc:70-151). All collective traffic then runs over the mesh
sockets from the engine's single background thread, so no framing tags
are needed beyond a length prefix (the reference relies on the same
single-communication-thread invariant, ref: operations.cc:332-351).

Control plane is star-topology at rank 0 (like MPIController's
Gather/Bcast, ref: mpi_controller.cc:108-199); the data-plane algorithms
come from StarCollectivesMixin. On TPU hardware the data plane is
XLA/ICI — this path serves CPU process-mode and tests; the C++ engine
(horovod_tpu/cc) supersedes it for performance.

Fault tolerance (docs/fault_tolerance.md): every peer send/recv is
bounded (HOROVOD_TCP_TIMEOUT_SECONDS, polled so dead-peer FINs are
seen even when unbounded), connects retry with backoff + jitter, and
any transport failure is translated to TransportError — the
HorovodInternalError subclass the elastic contract keys on — with the
failed connection hard-closed so later ops fail fast. The
HOROVOD_FAULT_INJECT chaos harness (common/fault_injection.py) hooks
the same choke points.

Zero-copy framing: sends are scatter-gather (`sendmsg([header,
payload...])` — no length-prefix concat copy, numpy chunks go to the
wire as memoryviews) and receives land via `recv_into` on a byte
cursor over a caller- or freshly-allocated buffer, so a frame costs
zero intermediate copies in userspace. Ring data-plane sends ride a
persistent queue-fed sender thread per peer (created at the first send
to that peer, drained on shutdown/sever); EVERY send to a peer — sync
control plane, async ring, any executor channel — flows through that
FIFO, so frames can never interleave mid-frame even with concurrent
channel executors.

Channel-tagged frames: the header carries a 1-byte channel tag
(executor channel for pipelined data-plane ops, CTRL_CHANNEL for
control-plane traffic). A per-peer receive demultiplexer routes frames
to per-channel inboxes, so two in-flight collectives sharing one
socket can never steal each other's payloads: whichever thread is
reading the socket delivers frames for other channels into their
inboxes and keeps its zero-copy recv-into only for its own.

Liveness plane (common/health.py, docs/fault_tolerance.md): heartbeat
frames ride the same sockets under HEALTH_CHANNEL — consumed by
whichever thread reads them (plus an idle drain for sockets nobody is
reading), never deposited, never awaited. Every received frame stamps
per-peer activity, `declare_dead(peer, reason)` latches a liveness
verdict as the peer's root cause and severs it, and every
TransportError carries peer/reporter/root-cause attribution.

Pluggable transports (backend/transport.py, docs/running.md
"Transports"): every peer's bytes flow through a Transport object —
the socket machinery above wrapped as TcpTransport by default, plus a
shared-memory overlay (backend/shm.py: per-pair mmap rings and, for
fully co-located jobs, the arena) for co-located data-channel
traffic. Control and heartbeat frames ALWAYS stay on the sockets:
their FIN/RST + silence detection is what bounds failure detection,
and a verdict severs the peer's socket and shm lanes together.
"""
from __future__ import annotations

import collections
import os
import select
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import fault_injection
from ..common.exceptions import HorovodInternalError, TransportError
from ..utils import clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from ..utils.retry import call_with_retry
from .base import (
    CTRL_CHANNEL,
    HEALTH_CHANNEL,
    current_channel,
    desync_message,
    is_data_channel,
)
from .rendezvous import RendezvousClient
from .ring import RingCollectivesMixin
from .star import as_byte_view, join_buffers
from .transport import (
    FRAME_HDR,
    PeerSender,
    SendTicket,
    Transport,
    create_transport,
    register_transport,
)

logger = get_logger()

# Frame header: u64 payload length + u8 channel tag — the framing every
# transport shares (backend/transport.py). The tag is what lets
# concurrent executor channels share one peer socket safely.
_HDR = FRAME_HDR
_HDR_LEN = _HDR.size
# try_drain_idle reads already-buffered bytes in chunks of this size,
# and consumes at most _DRAIN_MAX_BYTES per call — liveness evidence,
# not throughput: a huge parked stream resumes at the next tick.
_DRAIN_CHUNK = 1 << 16
_DRAIN_MAX_BYTES = 4 << 20

# sendmsg is POSIX; the sequential-sendall fallback keeps exotic
# platforms working at the cost of one extra syscall per frame.
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


def _as_byte_views(data) -> List[memoryview]:
    """Normalize bytes | bytearray | memoryview | any buffer-protocol
    object (numpy arrays included) | a list/tuple of those into flat
    1-D byte memoryviews — zero-copy; buffers must be C-contiguous."""
    items = data if isinstance(data, (list, tuple)) else (data,)
    return [as_byte_view(item) for item in items]


def _send_all(sock: socket.socket, data, channel: int = CTRL_CHANNEL) -> int:
    """Frame + send without concatenation: one scatter-gather
    `sendmsg([header, *payload buffers])` in the common case, looping
    with memoryview cursors on partial sends. Accepts anything
    `_as_byte_views` does. Returns the payload byte count (header
    excluded)."""
    views = _as_byte_views(data)
    total = sum(len(v) for v in views)
    pending = [memoryview(_HDR.pack(total, channel))]
    pending += [v for v in views if len(v)]
    if not _HAS_SENDMSG:  # pragma: no cover - POSIX always has sendmsg
        for v in pending:
            sock.sendall(v)
        return total
    while pending:
        sent = sock.sendmsg(pending)
        while pending and sent >= len(pending[0]):
            sent -= len(pending[0])
            pending.pop(0)
        if pending and sent:
            pending[0] = pending[0][sent:]
    return total


def _make_poller(sock: socket.socket):
    """Readiness poller for the bounded-recv heartbeat, built once per
    recv (not per chunk — a 16MB transfer drains in hundreds of
    recv_into chunks). poll() where the platform has it — select()
    caps out at FD_SETSIZE (1024) and a big training process easily
    holds more fds than that; a peer socket with a high fileno must
    not be misdiagnosed as dead."""
    if hasattr(select, "poll"):
        p = select.poll()
        p.register(sock, select.POLLIN)
        return lambda poll_s: bool(p.poll(poll_s * 1000.0))
    return lambda poll_s: bool(  # pragma: no cover - POSIX has poll()
        select.select([sock], [], [], poll_s)[0])


def _recv_into(sock: socket.socket, view: memoryview):
    """Exact recv directly into a writable byte view (no accumulation
    buffer, no `buf += chunk` reallocation)."""
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed connection")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    _recv_into(sock, memoryview(buf))
    return buf


def _recv_frame(sock: socket.socket) -> bytearray:
    """Direct (pre-demux) frame read — bootstrap identification and
    framing tests only; the mesh's steady-state recvs go through the
    per-peer demultiplexer."""
    n, _ = _HDR.unpack(_recv_exact(sock, _HDR_LEN))
    return _recv_exact(sock, n)


def _recv_into_bounded(sock: socket.socket, view: memoryview,
                       timeout: float, poll: float):
    """Bounded recv-into: polls at `poll` granularity instead of
    blocking forever, so a dead peer is detected within `timeout`
    seconds of its last byte (or, if timeout == 0, the moment the OS
    delivers its FIN/RST — a process that dies, even via SIGKILL, still
    gets its sockets closed by the kernel). The deadline is an IDLE
    bound that resets on every received chunk, not a total-transfer
    bound: a live peer legitimately streaming a large payload for
    longer than the timeout must not be declared dead mid-transfer.
    This is the heartbeat the reference gets from gloo's
    timeout-bounded transports (ref: gloo store/ioTimeout).

    The poll uses select(), deliberately NOT settimeout: the socket's
    timeout is per-socket shared state that the peer's persistent
    sender worker also manipulates, and in a 2-rank ring the left and
    right neighbor are the SAME socket — a send completing mid-recv
    would reset the timeout under us and turn the heartbeat into an
    indefinite block."""
    got, n = 0, len(view)
    deadline = time.monotonic() + timeout if timeout > 0 else None
    if n:
        try:
            wait_readable = _make_poller(sock)
        except (OSError, ValueError):
            # fd hard-closed under us (a concurrent sever): same
            # contract as a peer death.
            raise ConnectionError("peer socket closed during recv") \
                from None
    while got < n:
        try:
            ready = wait_readable(poll)
        except (OSError, ValueError):
            raise ConnectionError("peer socket closed during recv") \
                from None
        if not ready:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"recv made no progress for {timeout:.1f}s "
                    f"(HOROVOD_TCP_TIMEOUT_SECONDS)"
                )
            continue
        try:
            r = sock.recv_into(view[got:])
        except (socket.timeout, TimeoutError):
            # A transient socket timeout set by the concurrent send
            # path tripped an otherwise-ready recv: treat as one poll
            # tick, the deadline logic above still bounds us.
            continue
        if not r:
            raise ConnectionError("peer closed connection")
        got += r
        if deadline is not None:
            deadline = time.monotonic() + timeout


def _recv_exact_bounded(sock: socket.socket, n: int,
                        timeout: float, poll: float) -> bytearray:
    """Bounded recv of n fresh bytes; the returned bytearray is owned
    exclusively by the caller, so unpack_array may alias it zero-copy."""
    buf = bytearray(n)
    _recv_into_bounded(sock, memoryview(buf), timeout, poll)
    return buf


# Completion ticket for queued sends — the extracted transport-layer
# machinery (backend/transport.py); the alias keeps the historical name
# importable.
_SendTicket = SendTicket


class _PeerSender(PeerSender):
    """Persistent queue-fed sender worker for one peer socket — the
    generic transport-layer PeerSender bound to this backend's
    `_peer_send_direct` (so fault-injection verdicts apply inside the
    worker: a delay rule stalls the queue and a sever fails the ticket
    exactly like the old inline send path did) and to the tracing
    plane's `tcp.sender_dwell` span. Created lazily at the first p2p
    send to the peer, reused for the backend's lifetime, drained on
    shutdown/sever. The queue holds memoryviews — enqueueing a ring
    segment costs no copy."""

    def __init__(self, backend: "TcpBackend", peer: int):
        self._backend = backend
        self.peer = peer
        super().__init__(
            send_fn=lambda payload, ch: backend._peer_send_direct(
                peer, payload, ch),
            label=f"peer {peer}",
            trace_emit=self._emit_dwell,
        )

    def send(self, payload, channel: int = CTRL_CHANNEL) -> _SendTicket:
        return super().send(payload, channel)

    def _emit_dwell(self, channel: int, t_enq: int, trace_id):
        # Dwell = enqueue to wire-complete, measured across the thread
        # hop; the trace id was captured on the CALLER's thread (the
        # worker has no trace scope of its own), like the channel tag.
        tr = self._backend.tracer
        if tr.enabled and channel != HEALTH_CHANNEL:
            tr.emit("tcp.sender_dwell", "xfer", t_enq,
                    clock.mono_ns() - t_enq, trace_id=trace_id,
                    args={"peer": self.peer, "channel": channel})


class _PeerDemux:
    """Receive demultiplexer state for one peer socket. Exactly one
    thread at a time reads the socket (`reading` flag under `cond`);
    frames tagged for other channels are deposited into their per-
    channel inboxes and waiters are notified. Consumers: one thread per
    channel by construction (each channel has one executor; the control
    plane is the single background thread), but the structure doesn't
    rely on it."""

    __slots__ = ("cond", "inbox", "reading", "partial")

    def __init__(self):
        self.cond = threading.Condition()
        self.inbox: Dict[int, "collections.deque"] = {}
        self.reading = False
        # Raw stream bytes (header first) of a frame the idle drain
        # started consuming but could not finish without blocking;
        # resumed by the next drain, or completed by whichever normal
        # reader takes the socket first. Only touched while holding the
        # `reading` flag.
        self.partial = bytearray()

    def take(self, channel: int) -> Optional[bytearray]:
        q = self.inbox.get(channel)
        return q.popleft() if q else None


class TcpTransport(Transport):
    """The socket mesh's per-peer endpoint, as a Transport: a thin
    binding of the backend's framing / channel-demux / persistent-
    sender machinery to one peer. The mesh backend routes every byte
    through a Transport object (this one by default; the shm overlay
    for co-located data lanes), so the conformance suite exercises the
    same interface against every implementation."""

    name = "tcp"

    def __init__(self, backend: "TcpBackend", peer: int):
        self.backend = backend
        self.peer = peer

    def send(self, payload, channel: int) -> None:
        self.backend._tcp_send(self.peer, payload, channel)

    def send_async(self, payload, channel: int):
        return self.backend._sender_for(self.peer).send(payload, channel)

    def recv(self, channel: int) -> bytearray:
        return self.backend._demux_recv(self.peer, channel, None)

    def recv_into(self, view: memoryview, channel: int) -> int:
        self.backend._demux_recv(self.peer, channel, view)
        return len(view)

    def sever(self) -> None:
        self.backend._sever(self.peer)

    @property
    def alive(self) -> bool:
        return self.peer in self.backend.peers

    def drain_idle(self, max_frames: int = 64) -> int:
        return self.backend._tcp_drain_idle(self.peer, max_frames)

    def status(self) -> dict:
        return {"transport": self.name, "alive": self.alive}


register_transport(
    "tcp", lambda backend, peer, **kw: TcpTransport(backend, peer))


class TcpBackend(RingCollectivesMixin):
    """Full-mesh sockets; rank 0 doubles as the coordinator."""

    def __init__(
        self,
        rank: int,
        size: int,
        rendezvous: Optional[RendezvousClient] = None,
        scope: Optional[str] = None,
        registry=None,
    ):
        from ..common import telemetry

        if registry is None:
            registry = telemetry.default_registry()
        self._m_bytes_sent = registry.counter(
            "horovod_tcp_bytes_sent_total",
            "Bytes written to peer sockets (frame headers included)")
        self._m_bytes_recv = registry.counter(
            "horovod_tcp_bytes_recv_total",
            "Bytes read from peer sockets (frame headers included)")
        self._m_timeouts = registry.counter(
            "horovod_tcp_timeouts_total",
            "Peer I/O operations that hit HOROVOD_TCP_TIMEOUT_SECONDS")
        self._m_severed = registry.counter(
            "horovod_tcp_peers_severed_total",
            "Peer connections hard-closed after a transport failure")
        self._m_frames_sent = registry.counter(
            "horovod_tcp_sendmsg_frames_total",
            "Framed messages written to peer sockets (scatter-gather "
            "sendmsg sends)")
        self._m_ring_segments = registry.counter(
            "horovod_ring_segments_total",
            "Pipeline segments moved by ring collectives (send side)")
        self._m_hier_arena = registry.counter(
            "horovod_hier_arena_ops_total",
            "Hierarchical allreduces whose intra-host legs rode the "
            "per-host shared-memory arena (leader schedule)")
        self._m_sender_depth = registry.gauge(
            "horovod_sender_queue_depth",
            "Frames queued on persistent peer senders, summed over peers")
        self._m_sender_depth.set_function(self._sender_queue_depth)
        # Per-channel frame accounting (recv side, where the demux sees
        # every frame exactly once) — lazy per channel tag.
        self._registry = registry
        self._m_channel_frames: Dict[int, object] = {}
        # Liveness plane (common/health.py): per-peer root-cause death
        # verdicts (a declared-dead peer's TransportErrors carry the
        # verdict instead of a bare socket error), the health-frame
        # callback, and per-peer last-received-frame stamps (ANY frame
        # from a peer is liveness evidence, so a streaming collective
        # never reads as silence).
        self._death_lock = threading.Lock()
        self._death_reasons: Dict[int, str] = {}
        self._health_cb = None
        self._last_activity: Dict[int, float] = {}
        # Persistent per-peer sender workers (lazy; _senders_lock guards
        # the dict — the workers themselves are single-consumer queues).
        self._senders: Dict[int, _PeerSender] = {}
        self._senders_lock = threading.Lock()
        # Per-peer wire mutex: every frame write (worker or sync fast
        # path) runs under it, so two threads can never interleave a
        # frame mid-write even when the fast path bypasses the worker.
        self._wire_locks: Dict[int, threading.Lock] = {}
        # Per-peer receive demultiplexers (lazy; _demux_lock guards the
        # dict only — routing runs under each demux's own condition).
        self._demux: Dict[int, _PeerDemux] = {}
        self._demux_lock = threading.Lock()
        # Pluggable transport layer (backend/transport.py): every peer
        # gets a base TcpTransport over its mesh socket; co-located
        # peers additionally get a shared-memory overlay when the
        # launch-time HOROVOD_TRANSPORT allows it. Data-channel frames
        # route per call (env read each time, so paired benchmarks can
        # flip tcp<->shm between barrier-separated rounds); control
        # and heartbeat frames ALWAYS ride the sockets — the FIN/RST
        # is what keeps dead-peer detection bounded.
        self._transports: Dict[int, Transport] = {}
        self._overlays: Dict[int, Transport] = {}
        self.arena_set = None
        self._m_tbytes: Dict[Tuple[str, str], object] = {}
        self._m_shm_ring_full = None
        # Hot-path per-transport byte counters, bound ONCE like their
        # siblings above — the socket send/recv paths must not pay a
        # dict lookup per frame.
        self._m_tcp_sent = self._transport_counter("tcp", "sent")
        self._m_tcp_recv = self._transport_counter("tcp", "recv")
        self.rank = rank
        self.size = size
        if scope is None:
            # Elastic re-init: the driver bumps HOROVOD_MESH_SCOPE per
            # topology epoch (stale peer addresses must not be reused).
            scope = env_cfg.get_str(env_cfg.MESH_SCOPE, "hvd_mesh")
        self.peers: Dict[int, socket.socket] = {}
        # Data-plane I/O bounds + chaos hooks (docs/fault_tolerance.md).
        self._timeout = env_cfg.tcp_timeout_seconds()
        self._poll = env_cfg.tcp_poll_seconds()
        self._injector = fault_injection.get_injector()
        if size == 1:
            return
        if rendezvous is None:
            addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR, "127.0.0.1")
            port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
            if port == 0:
                raise RuntimeError(
                    "TcpBackend needs HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT "
                    "(set by the hvdrun launcher)"
                )
            rendezvous = RendezvousClient(addr, port)
        self._rendezvous = rendezvous
        self._connect_full_mesh(scope)
        for peer in self.peers:
            self._transports[peer] = create_transport("tcp", self, peer)
        if env_cfg.transport_mode() in ("shm", "auto"):
            # Local shm failures degrade to tcp via the pairwise ok-bit
            # vote inside; a rendezvous failure here propagates like
            # any other bootstrap KV failure — it must, because a rank
            # that published its ok bit but could not read its peers'
            # would otherwise route asymmetrically.
            self._setup_shm_overlays(scope)

    # ------------------------------------------------------------------
    def _connect_full_mesh(self, scope: str):
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", 0))
        listener.listen(self.size)
        try:
            self._bootstrap_mesh(scope, listener)
        except (OSError, TimeoutError) as exc:
            # Any transport failure the inner paths did not already
            # translate (rendezvous down past the retry budget, a peer
            # dying mid-identification, a stray socket error): honor the
            # no-raw-ConnectionError contract and drop every fd so
            # elastic retries cannot accumulate leaks.
            self._close_all_peers()
            raise TransportError(
                f"rank {self.rank}: mesh bootstrap failed: {exc}"
            ) from exc
        finally:
            # Idempotent: the specific error paths (and the success
            # path) close it themselves.
            listener.close()

    def _bootstrap_mesh(self, scope: str, listener: socket.socket):
        my_port = listener.getsockname()[1]
        # HOROVOD_MESH_ADDR separates the ADVERTISED address from the
        # slot identity: Spark-task slots carry logical hostnames
        # ("sparktaskN") that no resolver knows, so the executor-side
        # spawner pins the real address here (HOROVOD_HOSTNAME must
        # stay logical — spawn_identity and the elastic registry key
        # on it).
        my_host = (os.environ.get("HOROVOD_MESH_ADDR")
                   or os.environ.get(env_cfg.HOSTNAME) or "127.0.0.1")
        if os.environ.get("HVDRUN_FORCE_LOCAL") or my_host in (
            "localhost", "") or my_host.startswith("process-"):
            my_host = "127.0.0.1"
        self._rendezvous.put(scope, str(self.rank), f"{my_host}:{my_port}".encode())

        # Connect to all lower ranks; accept from all higher ranks. The
        # accept side is bounded: a higher rank that dies during
        # bootstrap (or never starts) must surface as an error here, not
        # an indefinite hang (ref: gloo's store_timeout on rendezvous).
        bootstrap_timeout = env_cfg.get_float(
            "HOROVOD_MESH_BOOTSTRAP_TIMEOUT", 300.0)
        bootstrap_deadline = time.monotonic() + bootstrap_timeout
        for peer in range(self.rank):
            addr = self._rendezvous.wait_get(scope, str(peer)).decode()
            host, port = addr.rsplit(":", 1)
            s = None
            try:
                self._injector.check_io(self.rank, peer, "connect")
                # Retry with backoff+jitter: under elastic churn a peer's
                # listener may briefly refuse between epochs even though
                # its rendezvous row is live (ref: gloo retries its
                # connectFullMesh pair dials the same way).
                s = call_with_retry(
                    lambda: socket.create_connection(
                        (host, int(port)),
                        timeout=min(60.0, bootstrap_timeout)),
                    what=f"connect to rank {peer} at {addr}",
                    retry_on=(ConnectionError, socket.timeout, TimeoutError),
                    deadline=bootstrap_deadline,
                )
                # create_connection's timeout sticks to the socket; clear
                # it (like the accept side does) or every post-bootstrap
                # send/recv would silently inherit a 60s bound even with
                # HOROVOD_TCP_TIMEOUT_SECONDS=0 (unbounded).
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # The identification frame must be inside the translate+
                # cleanup scope too: a peer that accepts then dies sends
                # RST here, and a raw ConnectionResetError would both
                # skip elastic recovery and leak every socket opened so
                # far on this retry.
                _send_all(s, struct.pack("<i", self.rank))
            except (OSError, TimeoutError) as exc:
                listener.close()
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                self._close_all_peers()
                raise TransportError(
                    f"rank {self.rank}: cannot connect to rank {peer} at "
                    f"{addr}: {exc}"
                ) from exc
            self.peers[peer] = s
        listener.settimeout(bootstrap_timeout)
        for _ in range(self.rank + 1, self.size):
            s = None
            try:
                s, _ = listener.accept()
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # The rank-frame read stays under the bootstrap timeout:
                # a peer that connects but never identifies (half-dead
                # host, stray port scan) must not wedge the job either.
                s.settimeout(bootstrap_timeout)
                (peer,) = struct.unpack("<i", _recv_frame(s))
                s.settimeout(None)
            except (socket.timeout, TimeoutError):
                # An accepted-but-unidentified socket is not in
                # self.peers yet; close it here or it leaks an fd on
                # every elastic retry.
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                missing = sorted(
                    set(range(self.rank + 1, self.size)) - set(self.peers))
                # Elastic retries catch HorovodInternalError and re-init;
                # abandoned sockets must not accumulate across retries.
                listener.close()
                self._close_all_peers()
                raise HorovodInternalError(
                    f"rank {self.rank}: mesh bootstrap timed out after "
                    f"{bootstrap_timeout:.0f}s waiting for rank(s) "
                    f"{missing} to connect (HOROVOD_MESH_BOOTSTRAP_TIMEOUT)"
                )
            except OSError:
                # A peer that connected then died mid-identification
                # (RST during elastic churn). Close the orphan socket
                # here — _connect_full_mesh's outer handler cleans up
                # the rest and translates to TransportError.
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                raise
            self.peers[peer] = s
        listener.close()
        logger.debug("rank %d: TCP mesh connected (%d peers)", self.rank, len(self.peers))

    # ------------------------------------------------------------------
    # pluggable transport layer: base tcp per peer + shm overlay for
    # co-located peers (backend/transport.py registry; docs/running.md
    # "Transports").
    def _locality_token(self) -> str:
        """Machine identity for transport selection: the LOGICAL
        hostname (HOROVOD_HOSTNAME — so multi-host simulations on one
        box are honored) plus the kernel boot id (so two real machines
        that happen to share a hostname are never mistaken for
        co-located)."""
        host = (os.environ.get(env_cfg.HOSTNAME)
                or socket.gethostname() or "?")
        boot = ""
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                boot = f.read().strip()
        except OSError:  # pragma: no cover - non-Linux
            pass
        return f"{host}|{boot}"

    def _transport_counter(self, transport: str, direction: str):
        key = (transport, direction)
        m = self._m_tbytes.get(key)
        if m is None:
            m = self._registry.counter(
                "horovod_transport_bytes_total",
                "Bytes moved by the data plane per transport and "
                "direction (frame headers included)",
                labels={"transport": transport, "direction": direction})
            self._m_tbytes[key] = m
        return m

    def _setup_shm_overlays(self, scope: str):
        """Establish mmap ring-buffer overlays with every co-located
        peer. Locality is agreed through the rendezvous KV (each rank
        publishes its token; ranks compare pairwise), and ring files
        are named by mesh scope + a rank-0-published nonce so two jobs
        on one host can never collide. Establishment is PAIRWISE
        AGREED: each rank publishes an ok bit after its local attempt,
        and a pair uses its overlay only when BOTH sides succeeded —
        a rank whose shm dir is unwritable degrades the whole pair to
        tcp, never half of it (a one-sided route would park the other
        side's recv on a ring nobody writes, forever under unbounded
        timeouts). Runs once at mesh init; the per-call route decision
        is `_route`."""
        from . import shm as shm_mod  # registers the "shm" factory

        my_loc = self._locality_token()
        self._rendezvous.put(scope, f"loc{self.rank}", my_loc.encode())
        if self.rank == 0:
            self._rendezvous.put(scope, "shm_nonce",
                                 os.urandom(6).hex().encode())
        nonce = self._rendezvous.wait_get(scope, "shm_nonce").decode()
        ring_bytes = env_cfg.shm_ring_bytes()
        base_dir = env_cfg.shm_dir()
        overlays: Dict[int, Transport] = {}
        arena = None
        ok = True
        try:
            colocated = []
            for peer in sorted(self.peers):
                loc = self._rendezvous.wait_get(
                    scope, f"loc{peer}").decode()
                if loc != my_loc:
                    continue
                colocated.append(peer)
                path = os.path.join(
                    base_dir, shm_mod.ring_file_name(scope, nonce,
                                                     self.rank, peer))
                t = create_transport(
                    "shm", self, peer, path=path, ring_bytes=ring_bytes,
                    timeout=self._timeout, poll=self._poll)
                t.activity_cb = self._note_activity
                t.health_cb = self._route_health
                # Ticket errors from the overlay's sender worker must
                # honor the attributed TransportError contract, exactly
                # like the socket sender's do (translated inside
                # _peer_send_direct).
                t.send_fn = (
                    lambda payload, ch, _t=t, _p=peer: self._overlay_call(
                        _p, "send to", _t._send_direct, payload, ch))
                t.m_sent = self._transport_counter("shm", "sent")
                t.m_recv = self._transport_counter("shm", "recv")
                if self._m_shm_ring_full is None:
                    self._m_shm_ring_full = self._registry.counter(
                        "horovod_shm_ring_full_total",
                        "Send stalls on a full shared-memory ring "
                        "(backpressure episodes)")
                t.m_ring_full = self._m_shm_ring_full
                overlays[peer] = t
            # The co-located-group ARENA (backend/shm.py ShmArena),
            # HOST-scoped: whenever this rank shares its host with
            # anyone, the group gets one arena — the whole world on a
            # fully co-located mesh (big allreduces skip the per-pair
            # rings entirely: SHM_ARENA_ALLREDUCE), or one host's local
            # group on a multi-host mesh (the leader schedule's
            # intra-host arena legs). Group membership comes from the
            # same KV locality rows on every rank, so arena existence
            # is collectively consistent (given the ok bits below).
            if colocated:
                arena = shm_mod.ShmArenaSet(
                    base_dir, scope, nonce,
                    group=[self.rank] + colocated, rank=self.rank,
                    slot_bytes=env_cfg.shm_slot_bytes(),
                    timeout=self._timeout)
                arena.dead_cb = self._arena_dead_reason
                arena.m_sent = self._transport_counter("shm", "sent")
                arena.m_recv = self._transport_counter("shm", "recv")
        except Exception as exc:
            # Local failure (unwritable shm dir, ENOSPC, mmap): unwind
            # EVERYTHING and vote not-ok — partial overlay sets must
            # never survive, the warning's "staying on tcp" has to be
            # literally true.
            ok = False
            for t in overlays.values():
                try:
                    t.close()
                except Exception:  # pragma: no cover - unwind
                    pass
            overlays = {}
            arena = None
            logger.warning(
                "rank %d: shm establishment failed locally, voting "
                "tcp-only: %s", self.rank, exc)
        self._rendezvous.put(scope, f"shmok{self.rank}",
                             b"1" if ok else b"0")
        # Pairwise agreement: drop overlays to peers whose OWN
        # establishment failed — both ends of a pair decide from the
        # same two bits, so the route stays symmetric by construction.
        peer_ok: Dict[int, bool] = {}
        for peer in list(overlays):
            bit = self._rendezvous.wait_get(scope, f"shmok{peer}")
            peer_ok[peer] = bit == b"1"
            if not peer_ok[peer]:
                overlays.pop(peer).close()
        self._overlays.update(overlays)
        # The arena is disabled for the whole GROUP when any member's
        # establishment failed (every member sees the same bits, so the
        # group decides identically) — a host that can't map shm votes
        # its whole host down, never half of it.
        if arena is not None and (
                not ok
                or any(not peer_ok.get(p, False)
                       for p in arena.group if p != self.rank)):
            arena.close()
            arena = None
        self.arena_set = arena
        if self._overlays:
            logger.debug(
                "rank %d: shm overlays established with peers %s "
                "(ring %d bytes, arena %s, dir %s)", self.rank,
                sorted(self._overlays), ring_bytes,
                self.arena_set is not None, base_dir)

    def _arena_dead_reason(self) -> Optional[str]:
        """Bound for arena barrier waits: the first liveness verdict —
        or any severed peer — anywhere in the mesh (a superset of the
        arena's co-located group: a dead remote leader must abort a
        member parked on a bcast barrier too). Heartbeats ride TCP, so
        a wedged or killed rank surfaces here within the detection
        window and every rank parked on an arena barrier unblocks with
        the attributed root cause."""
        with self._death_lock:
            if self._death_reasons:
                return next(iter(self._death_reasons.values()))
        if len(self.peers) != self.size - 1:
            return (f"rank {self.rank}: a peer connection was severed "
                    f"(surviving peers: {sorted(self.peers)})")
        return None

    def _route(self, peer: int, channel: int) -> Optional[Transport]:
        """The per-call transport decision: data-channel frames ride
        the shm overlay when one exists and HOROVOD_TRANSPORT currently
        allows it; control/heartbeat frames and everything else stay on
        the socket. Returns the overlay transport, or None for the
        built-in tcp path. Symmetric by construction: both ends hold
        the same overlay set (KV-agreed locality) and read the same
        env, so a frame's sender and receiver always pick the same
        lane."""
        if not self._overlays or not is_data_channel(channel):
            return None
        if env_cfg.transport_mode() == "tcp":
            return None
        t = self._overlays.get(peer)
        return t if t is not None and t.alive else None

    def _overlay_call(self, peer: int, what: str, fn, *args):
        """Run one overlay-transport op under the same sever+translate
        contract as the socket paths (TransportError passes through
        already attributed)."""
        try:
            return fn(*args)
        except (OSError, TimeoutError) as exc:
            if isinstance(exc, (socket.timeout, TimeoutError)):
                self._m_timeouts.inc()
            self._sever(peer)
            raise self._transport_error(peer, what, exc) from exc

    def transport_status(self) -> dict:
        """Live transport view for /status (docs/metrics.md)."""
        mode = env_cfg.transport_mode()
        peers = {}
        for peer in sorted(set(self.peers) | set(self._overlays)):
            ov = self._overlays.get(peer)
            peers[str(peer)] = {
                "base": "tcp",
                "connected": peer in self.peers,
                "overlay": ov.status() if ov is not None else None,
            }
        st = {"mode": mode, "peers": peers}
        if self.arena_set is not None:
            st["arena"] = self.arena_set.status()
        return st

    def prefers_leader_hierarchy(self) -> bool:
        """True when the leader-based two-level allreduce is the right
        cross-host schedule HERE: every co-located peer (the local
        group from the negotiated topology) is reachable over a live
        shm overlay, making the intra-host leader gather/bcast nearly
        free. Collective consistency comes from the engine's validity
        agreement (a bitwise AND across ranks), not from this local
        answer."""
        if env_cfg.transport_mode() == "tcp" or not self._overlays:
            return False
        L = self.local_size
        base = self.cross_rank * L
        return all(
            base + i == self.rank
            or (base + i in self._overlays and self._overlays[base + i].alive)
            for i in range(L)
        )

    def prefers_arena_hierarchy(self) -> bool:
        """Local vote for the leader schedule's host-arena intra-host
        legs: a live host arena covers EXACTLY this rank's local group
        from the negotiated topology (the locality rows and the
        hostfile agree on who shares the host). Folded into the
        engine's validity agreement like the leader vote — never
        consulted per call, so no rank can pick a different leg."""
        if env_cfg.transport_mode() == "tcp":
            return False
        aset = self.arena_set
        if aset is None:
            return False
        L = self.local_size
        base = self.cross_rank * L
        return aset.group == list(range(base, base + L))

    # ------------------------------------------------------------------
    # bounded, chaos-aware peer I/O. Every byte to or from a peer flows
    # through _peer_send/_peer_recv: fault-injection verdicts apply, any
    # OSError (dead peer, refused, reset) or deadline overrun is
    # translated to TransportError — the HorovodInternalError subclass
    # that triggers elastic restore — and the failed socket is hard-
    # closed so later ops on it fail fast instead of re-hanging.
    def _peer_sock(self, peer: int) -> socket.socket:
        s = self.peers.get(peer)
        if s is None:
            cause = self.death_reason(peer)
            if cause is not None:
                raise TransportError(cause, peer=peer, reporter=self.rank,
                                     root_cause=cause)
            raise TransportError(
                f"rank {self.rank}: connection to peer {peer} is down "
                f"(severed by an earlier transport failure)",
                peer=peer, reporter=self.rank,
            )
        return s

    def _transport_error(self, peer: int, what: str, exc) -> TransportError:
        """Translate a socket-level failure with `peer` into the
        attributed TransportError contract: when the liveness plane has
        already declared the peer dead, the verdict IS the message
        ("rank 2 (host X) declared dead: ..."), not the incidental
        socket error its sever produced."""
        cause = self.death_reason(peer)
        if cause is not None:
            return TransportError(cause, peer=peer, reporter=self.rank,
                                  root_cause=cause)
        return TransportError(
            f"rank {self.rank}: {what} peer {peer} failed: {exc}",
            peer=peer, reporter=self.rank,
        )

    def _sever(self, peer: int):
        with self._senders_lock:
            snd = self._senders.pop(peer, None)
        if snd is not None:
            # stop() only enqueues the sentinel, so this is safe from
            # the sender's own thread (its error path calls _sever).
            snd.stop()
        # The peer is severed as a whole: its shm overlay dies with its
        # socket, unblocking any I/O parked on either lane NOW — and a
        # hole in the group makes the arena unusable, so barrier waits
        # unblock too (with the death verdict via _arena_dead_reason).
        ov = self._overlays.pop(peer, None)
        if ov is not None:
            ov.sever()
        if self.arena_set is not None:
            cause = self.death_reason(peer) or f"peer {peer} severed"
            self.arena_set.sever(cause)
        s = self.peers.pop(peer, None)
        if s is not None:
            self._m_severed.inc()
            try:
                s.close()
            except OSError:  # pragma: no cover - already dead
                pass
        # Wake demux waiters parked on other channels' inboxes: their
        # next read attempt hits the severed-peer fast path instead of
        # polling out the remainder of a cond timeout.
        with self._demux_lock:
            d = self._demux.get(peer)
        if d is not None:
            with d.cond:
                d.cond.notify_all()

    # -- liveness plane (common/health.py) -----------------------------
    def set_health_callback(self, cb) -> None:
        """cb(peer, payload) is invoked for every HEALTH_CHANNEL frame,
        from whichever thread happened to read it off the socket."""
        self._health_cb = cb

    def declare_dead(self, peer: int, reason: str) -> None:
        """Liveness verdict: latch `reason` as the peer's root cause —
        every subsequent TransportError involving it carries the verdict
        instead of a bare socket error — and hard-close the connection
        so any I/O parked on it (unbounded recvs included) unblocks
        NOW. This is what makes detection bounded even with
        HOROVOD_TCP_TIMEOUT_SECONDS=0."""
        with self._death_lock:
            self._death_reasons.setdefault(peer, reason)
        self._sever(peer)

    def death_reason(self, peer: int):
        with self._death_lock:
            return self._death_reasons.get(peer)

    def _note_activity(self, peer: int) -> None:
        self._last_activity[peer] = time.monotonic()

    def peer_activity(self, peer: int):
        """Monotonic timestamp of the last complete frame received from
        `peer` (None before the first)."""
        return self._last_activity.get(peer)

    def _route_health(self, peer: int, payload) -> None:
        self._note_activity(peer)
        cb = self._health_cb
        if cb is not None:
            try:
                cb(peer, bytes(payload))
            except Exception:  # pragma: no cover - monitor must not kill I/O
                logger.exception("health callback failed")

    def try_drain_idle(self, peer: int, max_frames: int = 64) -> int:
        """Liveness sweep over EVERY transport to `peer`: drain the
        socket's kernel buffer (below) and observe shm overlay
        progress — the peer's ring write-cursor advancing proves life
        without consuming (there is no kernel buffer to free there),
        so a peer streaming a collective over shared memory while the
        control plane is quiet never reads as silence."""
        ov = self._overlays.get(peer)
        if ov is not None:
            try:
                ov.drain_idle(max_frames)
            except Exception:  # pragma: no cover - defensive
                logger.exception("shm drain for peer %d failed", peer)
        return self._tcp_drain_idle(peer, max_frames)

    def _tcp_drain_idle(self, peer: int, max_frames: int = 64) -> int:
        """Opportunistically consume frames parked in `peer`'s kernel
        buffer while NO other thread is reading its socket. The control
        plane's sequential gather parks on one rank while the other
        ranks' frames — heartbeats included — sit unread; without this
        those ranks would read as silent. Health frames are consumed;
        anything else is deposited into its channel inbox exactly as a
        foreign-channel read would be, so no payload is ever lost.

        Never blocks: only bytes already in the kernel buffer are read
        (poll(0)-guarded chunk reads), accumulating into a resumable
        per-peer stash (`_PeerDemux.partial`) that the next drain — or
        whichever normal reader takes the socket first — completes.
        EVERY byte consumed counts as progress evidence, and consuming
        frees rcvbuf so a flow-control-blocked peer keeps making
        progress: a peer mid-write of an arbitrarily large frame keeps
        proving life, while one genuinely stalled mid-frame accrues
        silence until the miss window declares it with full attribution
        (severing on a stalled read here would contradict the
        documented miss_limit x interval tolerance). Work per call is
        bounded by `max_frames` and _DRAIN_MAX_BYTES. Returns complete
        frames drained."""
        d = self._demux_for(peer)
        sock = self.peers.get(peer)
        if sock is None:
            return 0
        with d.cond:
            if d.reading:
                # The active reader routes health frames itself.
                return 0
            d.reading = True
        drained = 0
        consumed_bytes = 0
        progressed = False
        deposits: List[Tuple[int, bytearray]] = []
        try:
            poller = _make_poller(sock)
            while drained < max_frames and consumed_bytes < _DRAIN_MAX_BYTES:
                if len(d.partial) < _HDR_LEN:
                    need = _HDR_LEN - len(d.partial)
                else:
                    n, ch = _HDR.unpack_from(d.partial)
                    need = _HDR_LEN + n - len(d.partial)
                if not poller(0):
                    break
                try:
                    chunk = sock.recv(min(need, _DRAIN_CHUNK))
                except OSError:
                    # Reset under us: any stash died with the stream.
                    if d.partial:
                        self._sever(peer)
                    break
                if not chunk:
                    # Orderly FIN. Mid-frame it is a desynced stream;
                    # otherwise leave the close to the normal paths so
                    # attribution flows through them.
                    if d.partial:
                        self._sever(peer)
                    break
                d.partial += chunk
                consumed_bytes += len(chunk)
                progressed = True
                if len(d.partial) >= _HDR_LEN:
                    n, ch = _HDR.unpack_from(d.partial)
                    if len(d.partial) == _HDR_LEN + n:
                        payload = d.partial[_HDR_LEN:]
                        d.partial = bytearray()
                        self._count_frame(ch, n)
                        if ch == HEALTH_CHANNEL:
                            self._route_health(peer, payload)
                        else:
                            deposits.append((ch, payload))
                        drained += 1
            if progressed:
                self._note_activity(peer)
        finally:
            with d.cond:
                d.reading = False
                for ch, payload in deposits:
                    d.inbox.setdefault(
                        ch, collections.deque()).append(payload)
                d.cond.notify_all()
        return drained

    def _finish_partial(self, d: "_PeerDemux", sock, peer: int) -> None:
        """Complete a frame the idle drain started consuming (caller
        holds the `reading` flag). Bounded like any normal read; the
        completed frame is deposited exactly as a foreign-channel read
        would deposit it — even when it is tagged for the caller's own
        channel, the caller re-checks its inbox and takes it from
        there."""
        if len(d.partial) < _HDR_LEN:
            d.partial += _recv_exact_bounded(
                sock, _HDR_LEN - len(d.partial), self._timeout, self._poll)
        n, ch = _HDR.unpack_from(d.partial)
        need = _HDR_LEN + n - len(d.partial)
        if need > 0:
            d.partial += _recv_exact_bounded(
                sock, need, self._timeout, self._poll)
        payload = d.partial[_HDR_LEN:]
        d.partial = bytearray()
        self._count_frame(ch, n)
        self._note_activity(peer)
        if ch == HEALTH_CHANNEL:
            self._route_health(peer, payload)
        else:
            with d.cond:
                d.inbox.setdefault(
                    ch, collections.deque()).append(payload)

    # -- persistent sender plumbing ------------------------------------
    def _sender_queue_depth(self) -> float:
        with self._senders_lock:
            return float(sum(s.queue.qsize()
                             for s in self._senders.values()))

    def _sender_for(self, peer: int) -> _PeerSender:
        with self._senders_lock:
            snd = self._senders.get(peer)
            if snd is None:
                snd = _PeerSender(self, peer)
                self._senders[peer] = snd
            return snd

    def send_async(self, peer: int, payload, channel: Optional[int] = None
                   ) -> _SendTicket:
        """Queue a framed send on the peer's persistent sender worker
        and return a completion ticket (ring data-plane primitive:
        the send of one segment overlaps the caller's recv+reduce).
        The channel tag is captured on the CALLER's thread — the sender
        worker has no channel scope of its own. Routes to the shm
        overlay for co-located data-channel traffic."""
        self._peer_sock(peer)  # fail fast on a severed peer
        if channel is None:
            channel = current_channel()
        t = self._route(peer, channel)
        if t is not None:
            return self._overlay_call(peer, "send to",
                                      t.send_async, payload, channel)
        return self._sender_for(peer).send(payload, channel)

    def _wire_lock(self, peer: int) -> threading.Lock:
        with self._senders_lock:
            lk = self._wire_locks.get(peer)
            if lk is None:
                lk = self._wire_locks[peer] = threading.Lock()
            return lk

    def _peer_send(self, peer: int, data):
        """Synchronous framed send, routed per call: shm overlay for
        co-located data-channel traffic, socket otherwise."""
        channel = current_channel()
        t = self._route(peer, channel)
        if t is not None:
            self._peer_sock(peer)  # fail fast on a severed peer
            self._overlay_call(peer, "send to", t.send, data, channel)
            return
        self._tcp_send(peer, data, channel)

    def _tcp_send(self, peer: int, data, channel: Optional[int] = None):
        """Socket-path sync send. Fast path: when this channel has no
        frames pending on the peer's sender worker, write the socket
        directly under the wire mutex — two thread hops cheaper, which
        is most of a control round's latency on an idle mesh. Frames of
        OTHER channels may be overtaken; the receive demultiplexer
        exists to make that safe. With same-channel frames pending, the
        send queues behind them (FIFO within a channel is the ordering
        contract)."""
        self._peer_sock(peer)  # fail fast on a severed peer
        if channel is None:
            channel = current_channel()
        # No sender worker for this peer yet ⇒ nothing can be pending:
        # write directly (under the wire mutex) without spawning one —
        # a pure control-plane mesh stays thread-free.
        snd = self._senders.get(peer)
        if snd is None or snd.channel_idle(channel):
            self._peer_send_direct(peer, data, channel)
            return
        snd.send(data, channel).wait()

    def _peer_send_direct(self, peer: int, data, channel: int = CTRL_CHANNEL):
        sock = self._peer_sock(peer)
        try:
            if self._injector.active:
                if (self._injector.check_io(self.rank, peer, "send")
                        == fault_injection.DROP):
                    return
            # Wire mutex: the sender worker and the sync fast path must
            # never interleave a frame mid-write on one socket.
            with self._wire_lock(peer):
                if self._timeout > 0:
                    sock.settimeout(self._timeout)
                try:
                    sent = _send_all(sock, data, channel)
                    self._m_bytes_sent.inc(sent + _HDR_LEN)
                    self._m_tcp_sent.inc(sent + _HDR_LEN)
                    self._m_frames_sent.inc()
                finally:
                    if self._timeout > 0:
                        try:
                            sock.settimeout(None)
                        except OSError:
                            pass
        except (OSError, TimeoutError) as exc:
            if isinstance(exc, (socket.timeout, TimeoutError)):
                self._m_timeouts.inc()
            self._sever(peer)
            raise self._transport_error(peer, "send to", exc) from exc

    # -- receive demultiplexer -----------------------------------------
    def _demux_for(self, peer: int) -> _PeerDemux:
        with self._demux_lock:
            d = self._demux.get(peer)
            if d is None:
                d = self._demux[peer] = _PeerDemux()
            return d

    def _count_frame(self, channel: int, nbytes: int):
        self._m_bytes_recv.inc(nbytes + _HDR_LEN)
        self._m_tcp_recv.inc(nbytes + _HDR_LEN)
        m = self._m_channel_frames.get(channel)
        if m is None:
            label = ("ctrl" if channel == CTRL_CHANNEL
                     else "health" if channel == HEALTH_CHANNEL
                     else str(channel))
            m = self._registry.counter(
                "horovod_tcp_channel_frames_total",
                "Frames received per channel tag (ctrl = control plane)",
                labels={"channel": label})
            self._m_channel_frames[channel] = m
        m.inc()

    def _demux_recv(self, peer: int, channel: int,
                    view: Optional[memoryview]) -> Optional[bytearray]:
        """Receive the next frame tagged `channel` from `peer`. With
        `view` set, the payload lands in it zero-copy when this thread
        reads its own frame off the socket (one copy when another
        channel's reader deposited it); returns the owned bytearray
        otherwise. Exactly one thread reads the socket at a time; frames
        for other channels are deposited into their inboxes. A frame-
        length/`view`-length mismatch is a desynced peer: OSError, which
        the caller translates to sever + TransportError."""
        d = self._demux_for(peer)
        while True:
            with d.cond:
                while True:
                    buf = d.take(channel)
                    if buf is not None:
                        if view is None:
                            return buf
                        if len(buf) != len(view):
                            raise OSError(
                                desync_message(len(buf), len(view)))
                        view[:] = buf
                        return None
                    if not d.reading:
                        d.reading = True
                        break
                    # Another thread owns the socket; its own idle
                    # deadline bounds the wait. Wake on deposit/sever.
                    if not self.peers.get(peer):
                        raise ConnectionError(
                            "peer severed while awaiting demuxed frame")
                    d.cond.wait(self._poll)
            if d.partial:
                # The idle drain left a frame mid-consume: finish and
                # route it first (it may even be ours — the inbox
                # re-check on the next loop iteration picks it up).
                try:
                    self._finish_partial(d, self._peer_sock(peer), peer)
                finally:
                    with d.cond:
                        d.reading = False
                        d.cond.notify_all()
                continue
            deposit = None
            got_mine = False
            try:
                sock = self._peer_sock(peer)
                n, ch = _HDR.unpack(_recv_exact_bounded(
                    sock, _HDR_LEN, self._timeout, self._poll))
                if ch == channel:
                    if view is not None:
                        if n != len(view):
                            raise OSError(desync_message(n, len(view)))
                        _recv_into_bounded(sock, view, self._timeout,
                                           self._poll)
                        result = None
                    else:
                        result = _recv_exact_bounded(
                            sock, n, self._timeout, self._poll)
                    got_mine = True
                elif ch == HEALTH_CHANNEL:
                    # Heartbeats are consumed by whoever reads them —
                    # never deposited, never awaited.
                    self._route_health(peer, _recv_exact_bounded(
                        sock, n, self._timeout, self._poll))
                else:
                    deposit = (ch, _recv_exact_bounded(
                        sock, n, self._timeout, self._poll))
                self._count_frame(ch, n)
                self._note_activity(peer)
            finally:
                with d.cond:
                    d.reading = False
                    if deposit is not None:
                        d.inbox.setdefault(
                            deposit[0], collections.deque()
                        ).append(deposit[1])
                    d.cond.notify_all()
            if got_mine:
                return result

    def _peer_recv(self, peer: int) -> bytearray:
        try:
            if self._injector.active:
                self._injector.check_io(self.rank, peer, "recv")
            self._peer_sock(peer)  # fail fast on a severed peer
            channel = current_channel()
            t = self._route(peer, channel)
            if t is not None:
                return t.recv(channel)
            return self._demux_recv(peer, channel, None)
        except (OSError, TimeoutError) as exc:
            if isinstance(exc, (socket.timeout, TimeoutError)):
                self._m_timeouts.inc()
            self._sever(peer)
            raise self._transport_error(peer, "recv from", exc) from exc

    def recv_into_from(self, peer: int, buf) -> int:
        """Receive one p2p frame directly into a writable buffer (numpy
        slice, bytearray, memoryview) — the zero-copy recv the ring data
        plane reduces from. The frame length must match len(buf)
        exactly: the ring protocol is size-deterministic, so a mismatch
        means a desynced peer (e.g. HOROVOD_RING_SEGMENT_BYTES differing
        across ranks) and the stream position is unrecoverable."""
        view = as_byte_view(buf)
        try:
            if self._injector.active:
                self._injector.check_io(self.rank, peer, "recv")
            self._peer_sock(peer)  # fail fast on a severed peer
            channel = current_channel()
            t = self._route(peer, channel)
            if t is not None:
                return t.recv_into(view, channel)
            self._demux_recv(peer, channel, view)
            return len(view)
        except (OSError, TimeoutError) as exc:
            if isinstance(exc, (socket.timeout, TimeoutError)):
                self._m_timeouts.inc()
            self._sever(peer)
            raise self._transport_error(peer, "recv from", exc) from exc

    # ------------------------------------------------------------------
    # transport primitives. Payloads may be scatter-gather buffer lists
    # (star.pack_array): the wire path sendmsg's them as-is; only a
    # LOCALLY consumed payload (rank 0's own contribution) is joined.
    def gather_bytes(self, payload) -> Optional[List[bytes]]:
        if self.size == 1:
            return [join_buffers(payload)]
        if self.rank == 0:
            out = [join_buffers(payload)]
            for r in range(1, self.size):
                out.append(self._peer_recv(r))
            return out
        self._peer_send(0, payload)
        return None

    def bcast_bytes(self, payload):
        # Rank 0 gets its own payload back VERBATIM (possibly still a
        # buffer list): every current root-side caller either ignores
        # the return or passed a single blob, and joining eagerly would
        # cost an O(payload) copy nobody reads. Joined blobs only come
        # from the recv path.
        #
        # The root side attempts EVERY peer before raising: a death in
        # the middle of the send loop must not leave the peers after it
        # one round behind the survivors before it — the failed peer is
        # severed (all later I/O to it fails fast) and the first error
        # is raised once the round is consistent for everyone else.
        if self.size == 1:
            assert payload is not None
            return payload
        if self.rank == 0:
            assert payload is not None
            first_error: Optional[TransportError] = None
            for r in range(1, self.size):
                try:
                    self._peer_send(r, payload)
                except TransportError as exc:
                    if first_error is None:
                        first_error = exc
            if first_error is not None:
                raise first_error
            return payload
        return self._peer_recv(0)

    def bcast_bytes_lossy(self, payload) -> int:
        """Coordinator-only best-effort broadcast for terminal abort
        verdicts: deliver to every still-connected peer, swallowing
        per-peer transport failures — a dead peer must not stop the
        verdict from reaching the survivors. Returns how many peers
        were reached."""
        delivered = 0
        for r in range(1, self.size):
            if r not in self.peers:
                continue
            try:
                self._peer_send(r, payload)
                delivered += 1
            except HorovodInternalError:
                continue
        return delivered

    def scatter_bytes(self, payloads: Optional[List]) -> bytes:
        # Same verbatim-return contract as bcast_bytes (alltoallv joins
        # per_dest[0] itself when it actually decodes it).
        if self.size == 1:
            assert payloads is not None
            return payloads[0]
        if self.rank == 0:
            assert payloads is not None
            for r in range(1, self.size):
                self._peer_send(r, payloads[r])
            return payloads[0]
        return self._peer_recv(0)

    # ------------------------------------------------------------------
    def send_to(self, peer: int, payload):
        """Point-to-point framed send (ring data plane primitive).
        Accepts bytes | memoryview | numpy buffer | list of buffers —
        scatter-gathered to the wire without concatenation."""
        self._peer_send(peer, payload)

    def recv_from(self, peer: int) -> bytearray:
        return self._peer_recv(peer)

    def _close_all_peers(self):
        for s in self.peers.values():
            try:
                s.close()
            except OSError:
                pass
        self.peers.clear()

    def shutdown(self):
        # Drain the persistent senders first: the stop sentinel ends
        # each worker after in-flight frames; closing the sockets then
        # unblocks any worker stuck in a send (its ticket gets the
        # resulting TransportError instead of hanging a waiter).
        with self._senders_lock:
            senders = list(self._senders.values())
            self._senders.clear()
        for snd in senders:
            snd.stop()
        # Overlay transports close before the sockets: close() stops
        # their sender workers, marks the shared closed flag (the
        # peer's parked I/O unblocks) and unlinks the ring file.
        overlays = list(self._overlays.values())
        self._overlays.clear()
        for ov in overlays:
            try:
                ov.close()
            except Exception:  # pragma: no cover - teardown best-effort
                logger.exception("shm overlay close failed")
        if self.arena_set is not None:
            try:
                self.arena_set.close()
            except Exception:  # pragma: no cover - teardown best-effort
                logger.exception("shm arena close failed")
            self.arena_set = None
        self._close_all_peers()
        for snd in senders:
            snd.thread.join(timeout=5)
        # Detach the pull gauge so a dead backend is not pinned (and
        # reported as live) by the process-default registry — unless a
        # sibling backend (subset communicator) already took it over.
        self._m_sender_depth.clear_function(self._sender_queue_depth)
