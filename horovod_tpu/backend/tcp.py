"""TCP full-mesh backend: the Gloo-equivalent control+data plane.

Workers rendezvous through the HTTP KV store (each PUTs its listening
address, then connects to every lower rank — the same connectFullMesh
bootstrap gloo performs against the KV store, ref: horovod/common/gloo/
gloo_context.cc:70-151). All collective traffic then runs over the mesh
sockets from the engine's single background thread, so no framing tags
are needed beyond a length prefix (the reference relies on the same
single-communication-thread invariant, ref: operations.cc:332-351).

Control plane is star-topology at rank 0 (like MPIController's
Gather/Bcast, ref: mpi_controller.cc:108-199); the data-plane algorithms
come from StarCollectivesMixin. On TPU hardware the data plane is
XLA/ICI — this path serves CPU process-mode and tests; the C++ engine
(horovod_tpu/cc) supersedes it for performance.

Fault tolerance (docs/fault_tolerance.md): every peer send/recv is
bounded (HOROVOD_TCP_TIMEOUT_SECONDS, polled so dead-peer FINs are
seen even when unbounded), connects retry with backoff + jitter, and
any transport failure is translated to TransportError — the
HorovodInternalError subclass the elastic contract keys on — with the
failed connection hard-closed so later ops fail fast. The
HOROVOD_FAULT_INJECT chaos harness (common/fault_injection.py) hooks
the same choke points.
"""
from __future__ import annotations

import os
import socket
import struct
import time
from typing import Dict, List, Optional

from ..common import fault_injection
from ..common.exceptions import HorovodInternalError, TransportError
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from ..utils.retry import call_with_retry
from .rendezvous import RendezvousClient
from .ring import RingCollectivesMixin

logger = get_logger()

_LEN = struct.Struct("<Q")


def _send_all(sock: socket.socket, data: bytes):
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, 8))
    return _recv_exact(sock, n)


def _recv_exact_bounded(sock: socket.socket, n: int,
                        timeout: float, poll: float) -> bytes:
    """Bounded recv: polls at `poll` granularity instead of blocking
    forever, so a dead peer is detected within `timeout` seconds of its
    last byte (or, if timeout == 0, the moment the OS delivers its
    FIN/RST — a process that dies, even via SIGKILL, still gets its
    sockets closed by the kernel). The deadline is an IDLE bound that
    resets on every received chunk, not a total-transfer bound: a live
    peer legitimately streaming a large payload for longer than the
    timeout must not be declared dead mid-transfer. This is the
    heartbeat the reference gets from gloo's timeout-bounded transports
    (ref: gloo store/ioTimeout)."""
    buf = bytearray()
    deadline = time.monotonic() + timeout if timeout > 0 else None
    prev = sock.gettimeout()
    sock.settimeout(poll)
    try:
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except (socket.timeout, TimeoutError):
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"recv made no progress for {timeout:.1f}s "
                        f"(HOROVOD_TCP_TIMEOUT_SECONDS)"
                    ) from None
                continue
            if not chunk:
                raise ConnectionError("peer closed connection")
            buf.extend(chunk)
            if deadline is not None:
                deadline = time.monotonic() + timeout
        return bytes(buf)
    finally:
        try:
            sock.settimeout(prev)
        except OSError:  # pragma: no cover - socket already dead
            pass


class TcpBackend(RingCollectivesMixin):
    """Full-mesh sockets; rank 0 doubles as the coordinator."""

    def __init__(
        self,
        rank: int,
        size: int,
        rendezvous: Optional[RendezvousClient] = None,
        scope: Optional[str] = None,
        registry=None,
    ):
        from ..common import telemetry

        if registry is None:
            registry = telemetry.default_registry()
        self._m_bytes_sent = registry.counter(
            "horovod_tcp_bytes_sent_total",
            "Bytes written to peer sockets (frame headers included)")
        self._m_bytes_recv = registry.counter(
            "horovod_tcp_bytes_recv_total",
            "Bytes read from peer sockets (frame headers included)")
        self._m_timeouts = registry.counter(
            "horovod_tcp_timeouts_total",
            "Peer I/O operations that hit HOROVOD_TCP_TIMEOUT_SECONDS")
        self._m_severed = registry.counter(
            "horovod_tcp_peers_severed_total",
            "Peer connections hard-closed after a transport failure")
        self.rank = rank
        self.size = size
        if scope is None:
            # Elastic re-init: the driver bumps HOROVOD_MESH_SCOPE per
            # topology epoch (stale peer addresses must not be reused).
            scope = env_cfg.get_str(env_cfg.MESH_SCOPE, "hvd_mesh")
        self.peers: Dict[int, socket.socket] = {}
        # Data-plane I/O bounds + chaos hooks (docs/fault_tolerance.md).
        self._timeout = env_cfg.tcp_timeout_seconds()
        self._poll = env_cfg.tcp_poll_seconds()
        self._injector = fault_injection.get_injector()
        if size == 1:
            return
        if rendezvous is None:
            addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR, "127.0.0.1")
            port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
            if port == 0:
                raise RuntimeError(
                    "TcpBackend needs HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT "
                    "(set by the hvdrun launcher)"
                )
            rendezvous = RendezvousClient(addr, port)
        self._rendezvous = rendezvous
        self._connect_full_mesh(scope)

    # ------------------------------------------------------------------
    def _connect_full_mesh(self, scope: str):
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", 0))
        listener.listen(self.size)
        try:
            self._bootstrap_mesh(scope, listener)
        except (OSError, TimeoutError) as exc:
            # Any transport failure the inner paths did not already
            # translate (rendezvous down past the retry budget, a peer
            # dying mid-identification, a stray socket error): honor the
            # no-raw-ConnectionError contract and drop every fd so
            # elastic retries cannot accumulate leaks.
            self._close_all_peers()
            raise TransportError(
                f"rank {self.rank}: mesh bootstrap failed: {exc}"
            ) from exc
        finally:
            # Idempotent: the specific error paths (and the success
            # path) close it themselves.
            listener.close()

    def _bootstrap_mesh(self, scope: str, listener: socket.socket):
        my_port = listener.getsockname()[1]
        # HOROVOD_MESH_ADDR separates the ADVERTISED address from the
        # slot identity: Spark-task slots carry logical hostnames
        # ("sparktaskN") that no resolver knows, so the executor-side
        # spawner pins the real address here (HOROVOD_HOSTNAME must
        # stay logical — spawn_identity and the elastic registry key
        # on it).
        my_host = (os.environ.get("HOROVOD_MESH_ADDR")
                   or os.environ.get(env_cfg.HOSTNAME) or "127.0.0.1")
        if os.environ.get("HVDRUN_FORCE_LOCAL") or my_host in (
            "localhost", "") or my_host.startswith("process-"):
            my_host = "127.0.0.1"
        self._rendezvous.put(scope, str(self.rank), f"{my_host}:{my_port}".encode())

        # Connect to all lower ranks; accept from all higher ranks. The
        # accept side is bounded: a higher rank that dies during
        # bootstrap (or never starts) must surface as an error here, not
        # an indefinite hang (ref: gloo's store_timeout on rendezvous).
        bootstrap_timeout = env_cfg.get_float(
            "HOROVOD_MESH_BOOTSTRAP_TIMEOUT", 300.0)
        bootstrap_deadline = time.monotonic() + bootstrap_timeout
        for peer in range(self.rank):
            addr = self._rendezvous.wait_get(scope, str(peer)).decode()
            host, port = addr.rsplit(":", 1)
            s = None
            try:
                self._injector.check_io(self.rank, peer, "connect")
                # Retry with backoff+jitter: under elastic churn a peer's
                # listener may briefly refuse between epochs even though
                # its rendezvous row is live (ref: gloo retries its
                # connectFullMesh pair dials the same way).
                s = call_with_retry(
                    lambda: socket.create_connection(
                        (host, int(port)),
                        timeout=min(60.0, bootstrap_timeout)),
                    what=f"connect to rank {peer} at {addr}",
                    retry_on=(ConnectionError, socket.timeout, TimeoutError),
                    deadline=bootstrap_deadline,
                )
                # create_connection's timeout sticks to the socket; clear
                # it (like the accept side does) or every post-bootstrap
                # send/recv would silently inherit a 60s bound even with
                # HOROVOD_TCP_TIMEOUT_SECONDS=0 (unbounded).
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # The identification frame must be inside the translate+
                # cleanup scope too: a peer that accepts then dies sends
                # RST here, and a raw ConnectionResetError would both
                # skip elastic recovery and leak every socket opened so
                # far on this retry.
                _send_all(s, struct.pack("<i", self.rank))
            except (OSError, TimeoutError) as exc:
                listener.close()
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                self._close_all_peers()
                raise TransportError(
                    f"rank {self.rank}: cannot connect to rank {peer} at "
                    f"{addr}: {exc}"
                ) from exc
            self.peers[peer] = s
        listener.settimeout(bootstrap_timeout)
        for _ in range(self.rank + 1, self.size):
            s = None
            try:
                s, _ = listener.accept()
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # The rank-frame read stays under the bootstrap timeout:
                # a peer that connects but never identifies (half-dead
                # host, stray port scan) must not wedge the job either.
                s.settimeout(bootstrap_timeout)
                (peer,) = struct.unpack("<i", _recv_frame(s))
                s.settimeout(None)
            except (socket.timeout, TimeoutError):
                # An accepted-but-unidentified socket is not in
                # self.peers yet; close it here or it leaks an fd on
                # every elastic retry.
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                missing = sorted(
                    set(range(self.rank + 1, self.size)) - set(self.peers))
                # Elastic retries catch HorovodInternalError and re-init;
                # abandoned sockets must not accumulate across retries.
                listener.close()
                self._close_all_peers()
                raise HorovodInternalError(
                    f"rank {self.rank}: mesh bootstrap timed out after "
                    f"{bootstrap_timeout:.0f}s waiting for rank(s) "
                    f"{missing} to connect (HOROVOD_MESH_BOOTSTRAP_TIMEOUT)"
                )
            except OSError:
                # A peer that connected then died mid-identification
                # (RST during elastic churn). Close the orphan socket
                # here — _connect_full_mesh's outer handler cleans up
                # the rest and translates to TransportError.
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                raise
            self.peers[peer] = s
        listener.close()
        logger.debug("rank %d: TCP mesh connected (%d peers)", self.rank, len(self.peers))

    # ------------------------------------------------------------------
    # bounded, chaos-aware peer I/O. Every byte to or from a peer flows
    # through _peer_send/_peer_recv: fault-injection verdicts apply, any
    # OSError (dead peer, refused, reset) or deadline overrun is
    # translated to TransportError — the HorovodInternalError subclass
    # that triggers elastic restore — and the failed socket is hard-
    # closed so later ops on it fail fast instead of re-hanging.
    def _peer_sock(self, peer: int) -> socket.socket:
        s = self.peers.get(peer)
        if s is None:
            raise TransportError(
                f"rank {self.rank}: connection to peer {peer} is down "
                f"(severed by an earlier transport failure)"
            )
        return s

    def _sever(self, peer: int):
        s = self.peers.pop(peer, None)
        if s is not None:
            self._m_severed.inc()
            try:
                s.close()
            except OSError:  # pragma: no cover - already dead
                pass

    def _peer_send(self, peer: int, data: bytes):
        sock = self._peer_sock(peer)
        try:
            if self._injector.active:
                if (self._injector.check_io(self.rank, peer, "send")
                        == fault_injection.DROP):
                    return
            if self._timeout > 0:
                sock.settimeout(self._timeout)
            try:
                _send_all(sock, data)
                self._m_bytes_sent.inc(len(data) + 8)
            finally:
                if self._timeout > 0:
                    try:
                        sock.settimeout(None)
                    except OSError:
                        pass
        except (OSError, TimeoutError) as exc:
            if isinstance(exc, (socket.timeout, TimeoutError)):
                self._m_timeouts.inc()
            self._sever(peer)
            raise TransportError(
                f"rank {self.rank}: send to peer {peer} failed: {exc}"
            ) from exc

    def _peer_recv(self, peer: int) -> bytes:
        sock = self._peer_sock(peer)
        try:
            if self._injector.active:
                self._injector.check_io(self.rank, peer, "recv")
            (n,) = _LEN.unpack(
                _recv_exact_bounded(sock, 8, self._timeout, self._poll))
            data = _recv_exact_bounded(sock, n, self._timeout, self._poll)
            self._m_bytes_recv.inc(n + 8)
            return data
        except (OSError, TimeoutError) as exc:
            if isinstance(exc, (socket.timeout, TimeoutError)):
                self._m_timeouts.inc()
            self._sever(peer)
            raise TransportError(
                f"rank {self.rank}: recv from peer {peer} failed: {exc}"
            ) from exc

    # ------------------------------------------------------------------
    # transport primitives
    def gather_bytes(self, payload: bytes) -> Optional[List[bytes]]:
        if self.size == 1:
            return [payload]
        if self.rank == 0:
            out = [payload]
            for r in range(1, self.size):
                out.append(self._peer_recv(r))
            return out
        self._peer_send(0, payload)
        return None

    def bcast_bytes(self, payload: Optional[bytes]) -> bytes:
        if self.size == 1:
            assert payload is not None
            return payload
        if self.rank == 0:
            assert payload is not None
            for r in range(1, self.size):
                self._peer_send(r, payload)
            return payload
        return self._peer_recv(0)

    def scatter_bytes(self, payloads: Optional[List[bytes]]) -> bytes:
        if self.size == 1:
            assert payloads is not None
            return payloads[0]
        if self.rank == 0:
            assert payloads is not None
            for r in range(1, self.size):
                self._peer_send(r, payloads[r])
            return payloads[0]
        return self._peer_recv(0)

    # ------------------------------------------------------------------
    def send_to(self, peer: int, payload: bytes):
        """Point-to-point framed send (ring data plane primitive)."""
        self._peer_send(peer, payload)

    def recv_from(self, peer: int) -> bytes:
        return self._peer_recv(peer)

    def _close_all_peers(self):
        for s in self.peers.values():
            try:
                s.close()
            except OSError:
                pass
        self.peers.clear()

    def shutdown(self):
        self._close_all_peers()
