"""Data-plane backend interface.

The reference dispatches each collective to the first enabled op in an
ordered backend list (NCCL → MPI → Gloo → CPU; ref: horovod/common/
operations.cc:142-249 CreateOperationManager, ops/operation_manager.cc:
42-122). The TPU build has two data planes:

  * XLA collectives over ICI — the traced path (ops/traced.py); and
  * a host-side backend for the eager process-mode engine, operating on
    numpy buffers: TCP full mesh (Gloo-equivalent) or trivial local.

This module defines the interface both the engine and the controller
transport use.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..common import tracing
from ..common.types import ReduceOp
from ..engine.controller import ControllerTransport

# Reserved frame tag for control-plane traffic (negotiation gathers,
# cache bitvector passes, fenced barriers — everything issued from the
# engine's background thread outside a channel scope). Data channels are
# 0..MAX_CHANNELS-1 and can never collide with it.
CTRL_CHANNEL = 0xFF

# Reserved frame tag for the liveness plane (common/health.py):
# heartbeat/ack frames ride the existing peer sockets but are consumed
# by whichever thread happens to be reading — they are never deposited
# into a demux inbox, never awaited, and never block a collective.
HEALTH_CHANNEL = 0xFE

def is_data_channel(channel: int) -> bool:
    """True for executor data lanes (0..MAX_CHANNELS-1). Control-plane
    and heartbeat frames are NOT data: they always ride the TCP mesh —
    the socket is the liveness substrate — while data channels may be
    routed to a per-peer overlay transport (shared memory for
    co-located ranks, HOROVOD_TRANSPORT)."""
    return channel < HEALTH_CHANNEL


# The active executor channel is thread-scoped, not call-threaded: one
# thread runs one response at a time, so a thread-local avoids plumbing
# a channel argument through every collective signature (engine op
# registry -> mixin -> transport primitive). Module-level because
# backends don't share an __init__ to hang per-instance state on; a
# thread only ever executes for one backend inside a scope.
_channel_ctx = threading.local()


def current_channel() -> int:
    """Channel tag for data-plane frames issued by the calling thread;
    CTRL_CHANNEL outside any scope (control plane, direct backend use)."""
    return getattr(_channel_ctx, "channel", CTRL_CHANNEL)


@contextlib.contextmanager
def channel_scope(channel: int):
    prev = getattr(_channel_ctx, "channel", None)
    _channel_ctx.channel = channel
    try:
        yield
    finally:
        if prev is None:
            del _channel_ctx.channel
        else:
            _channel_ctx.channel = prev


# Active wire codec (docs/running.md "Wire compression"), thread-scoped
# exactly like the channel: the engine sets it around each response
# whose coordinator-assigned codec id is non-zero, and the data-plane
# paths (ring segments, star frames, shm arena deposits) read it
# instead of having a codec argument threaded through every collective
# signature. Outside any scope — direct backend use, control plane —
# there is no codec and every path behaves exactly as before.
_codec_ctx = threading.local()


def current_wire_codec():
    """The calling thread's active wire codec (common/compression.py
    WireCodec), or None."""
    return getattr(_codec_ctx, "codec", None)


def wire_codec_stats():
    """The active codec scope's telemetry sink
    (common/compression.py CompressionStats), or None."""
    return getattr(_codec_ctx, "stats", None)


def take_first_hop_encoded(expected_nbytes: int):
    """Consume the scope's first-hop encoded bytes (the engine's
    error-feedback grid projection already encoded this response's
    contribution — docs/running.md "Wire compression"). Consume-once:
    the FIRST data-plane hop of the op ships these bytes directly
    instead of re-encoding; every later hop (which carries reduced,
    i.e. different, values) sees None and encodes itself. Callers must
    take this at their entry point, while their buffer still holds the
    engine's projected values, and pass it down explicitly — a nested
    ring on mutated data must never see the stash. The size check is
    defense in depth: a buffer the engine didn't project (different
    element count) can never match."""
    enc = getattr(_codec_ctx, "first_hop", None)
    if enc is None:
        return None
    _codec_ctx.first_hop = None
    if enc.nbytes != int(expected_nbytes):
        return None
    return enc


@contextlib.contextmanager
def wire_codec_scope(codec, stats=None, first_hop=None):
    prev = (getattr(_codec_ctx, "codec", None),
            getattr(_codec_ctx, "stats", None),
            getattr(_codec_ctx, "first_hop", None))
    _codec_ctx.codec = codec
    _codec_ctx.stats = stats
    _codec_ctx.first_hop = first_hop
    try:
        yield
    finally:
        (_codec_ctx.codec, _codec_ctx.stats,
         _codec_ctx.first_hop) = prev


def desync_message(got, want, rank: Optional[int] = None,
                   peer: Optional[int] = None) -> str:
    """The one place the frame-length-mismatch ("desynced peer") error
    text and its env-knob hint live. Ring protocols are
    size-deterministic, so a length mismatch means the stream position
    is unrecoverable — every transport (TCP, shm, in-process) raises
    this same message so the hint can never drift. The two knobs that
    change frame sizes are the ring segment size and the wire codec
    (a half-width bf16 frame meeting a full-width reader is exactly
    this error); both are collectively agreed in-band (wire-carried
    codec ids, launcher-propagated segment bytes), so hitting this
    means version skew or hand-driven backends disagreeing."""
    who = f"rank {rank}: " if rank is not None else ""
    src = f" from peer {peer}" if peer is not None else ""
    return (f"{who}frame length {got} != expected {want}{src} "
            f"(desynced peer; check HOROVOD_RING_SEGMENT_BYTES and "
            f"HOROVOD_WIRE_COMPRESSION match on every rank)")


class Backend(ControllerTransport):
    """Combined control-plane transport + data-plane collectives
    (the reference splits these into Controller and ops; the TCP socket
    mesh naturally serves both, as Gloo does in the reference)."""

    rank: int = 0
    size: int = 1
    # Host topology (ref: Controller rank/local_rank/cross_rank state,
    # controller.h:172-188). Set by the engine via set_topology(); the
    # hierarchical data plane needs it.
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1
    # Hierarchical allreduce toggle (ref: HOROVOD_HIERARCHICAL_ALLREDUCE,
    # operations.cc:416-513; autotune may flip it at sync boundaries).
    hierarchical: bool = False
    # Hierarchical allgather toggle (ref: HOROVOD_HIERARCHICAL_ALLGATHER,
    # MPIHierarchicalAllgather) — set by the engine from the collectively
    # agreed topology validity.
    hier_allgather: bool = False
    # Leader-based cross-host schedule allowed (HOROVOD_HIERARCHICAL_MODE
    # =auto resolves through this): set by the ENGINE from a collectively
    # AND-agreed capability bit — every co-located pair on every host has
    # a live shared-memory overlay — so no rank can pick a different
    # schedule. Tests may set it directly on hand-built backends.
    leader_hier_ok: bool = False
    # Host-arena intra-host legs allowed (HOROVOD_HIER_ARENA=auto
    # resolves through this): set by the ENGINE from a collectively
    # AND-agreed capability bit — every host's local group is covered
    # by a live shared-memory arena — so a host that cannot map its
    # arena degrades the whole schedule to per-pair rings consistently.
    # Tests may set it directly on hand-built backends.
    arena_hier_ok: bool = False
    # Intra-host collective arena (backend/shm.py ShmArenaSet), set by
    # mesh backends for the co-located group agreed via the rendezvous
    # locality rows: the whole world when fully co-located (the
    # SHM_ARENA_ALLREDUCE plane, backend/ring.py arena_eligible) or one
    # host's local group on a multi-host mesh (the leader schedule's
    # arena legs).
    arena_set = None

    def prefers_leader_hierarchy(self) -> bool:
        """This rank's LOCAL vote for the leader schedule (intra-host
        bytes ~free, e.g. over shm). Folded into the engine's validity
        agreement; never consulted directly by the data plane."""
        return False

    def prefers_arena_hierarchy(self) -> bool:
        """This rank's LOCAL vote for host-arena intra-host legs: its
        local group (from the negotiated topology) is exactly the
        co-located group a live host arena covers. Folded into the
        engine's validity agreement like the leader vote."""
        return False
    # Tracing plane (common/tracing.py): the engine installs its tracer
    # here so backend phase spans (ring segment recv/reduce, star
    # gather/bcast, TCP sender dwell) land in the same flight recorder
    # as the engine's. Inert by default — a backend used outside an
    # engine records nothing.
    tracer: tracing.Tracer = tracing.NULL_TRACER

    def channel_scope(self, channel: int):
        """Context manager tagging this thread's data-plane traffic with
        an executor channel (engine sets it around each response). The
        tag rides the TCP frame header so two in-flight collectives on
        one socket demultiplex instead of interleaving payloads."""
        return channel_scope(channel)

    def current_channel(self) -> int:
        return current_channel()

    def set_topology(self, local_rank: int, local_size: int,
                     cross_rank: int, cross_size: int):
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size

    # -- data plane -----------------------------------------------------
    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        raise NotImplementedError

    def allgatherv(self, arr: np.ndarray, first_dims: List[int]) -> np.ndarray:
        """Concatenate per-rank arrays along dim 0; `first_dims[r]` is rank
        r's first-dim size (ref: AllgatherOp displacement math,
        collective_operations.h:148-185)."""
        raise NotImplementedError

    def broadcast(self, arr: Optional[np.ndarray], root: int) -> np.ndarray:
        raise NotImplementedError

    def alltoallv(
        self, arr: np.ndarray, splits: List[int]
    ) -> Tuple[np.ndarray, List[int]]:
        """Send splits[r] rows to rank r; returns (received, recv_splits)
        (ref: AlltoallOp, collective_operations.h:206-256)."""
        raise NotImplementedError

    def shutdown(self):
        pass


_NATIVE_OP = {
    ReduceOp.SUM: "sum",
    ReduceOp.AVERAGE: "sum",
    ReduceOp.MIN: "min",
    ReduceOp.MAX: "max",
    ReduceOp.PRODUCT: "prod",
}


def _reduce(op: ReduceOp, arrays: List[np.ndarray]) -> np.ndarray:
    # Native C++ kernels first (threaded k-way reduce; ref: the C++ CPU
    # op layer, collective_operations.h:89-125); NumPy fallback.
    from ..cc import native

    name = _NATIVE_OP.get(op)
    if name is not None and len(arrays) > 1:
        out = native.reduce_arrays(name, arrays)
        if out is not None:
            if op == ReduceOp.AVERAGE:
                out = out / len(arrays)
            return out
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        out = arrays[0].copy()
        for a in arrays[1:]:
            out += a
        if op == ReduceOp.AVERAGE:
            out = out / len(arrays)
        return out
    if op == ReduceOp.MIN:
        return np.minimum.reduce(arrays)
    if op == ReduceOp.MAX:
        return np.maximum.reduce(arrays)
    if op == ReduceOp.PRODUCT:
        out = arrays[0].copy()
        for a in arrays[1:]:
            out *= a
        return out
    if op == ReduceOp.ADASUM:
        native_out = native.adasum(arrays)
        if native_out is not None:
            return native_out[0]
        from ..ops.adasum import adasum_numpy

        return adasum_numpy(arrays)[0]
    raise ValueError(f"unsupported op {op}")
