"""HTTP KV rendezvous client (worker side).

(ref: horovod/runner/http/http_client.py:17-45 read_data_from_kvstore /
put_data_into_kvstore; the C++ consumer is gloo_context.cc:70-151.)

Requests are HMAC-signed with the per-job secret from
HOROVOD_SECRET_KEY when one is set (ref: the reference's service-
protocol HMAC, runner/common/util/network.py:50-110; here extended to
the rendezvous KV — see runner/rendezvous_server.py).
"""
from __future__ import annotations

import http.client
import time
from typing import Optional

from ..utils.logging import get_logger
from ..utils.retry import call_with_retry

logger = get_logger()

_request_counter_cache = None


def _request_counter():
    # Cached: wait_get polls the KV store at 20Hz during bootstrap; the
    # registry lookup happens once, not per poll.
    global _request_counter_cache
    if _request_counter_cache is None:
        from ..common import telemetry

        _request_counter_cache = telemetry.counter(
            "horovod_rendezvous_requests_total",
            "HTTP requests issued against the rendezvous server "
            "(retries included)",
        )
    return _request_counter_cache


class RendezvousClient:
    def __init__(self, addr: str, port: int, timeout: float = 60.0,
                 secret_key: Optional[bytes] = None,
                 namespace: Optional[str] = None):
        self.addr = addr
        self.port = port
        self.timeout = timeout
        if secret_key is None:
            from ..runner.util import secret as secret_util

            secret_key = secret_util.key_from_env()
        self.secret_key = secret_key
        # Per-job KV namespace (docs/elastic.md "Sharing one rendezvous
        # server"): with HOROVOD_JOB_NAME set, every key this client
        # touches lives under jobs/<name>/ — two jobs sharing one
        # server cannot collide. The driver prefixes identically, so
        # the whole protocol (rank rows, epochs, readiness, health
        # verdicts, drain notices, goodput/alert mirrors) is scoped
        # without any key-by-key opt-in. None = read the env; "" =
        # explicitly unnamespaced.
        if namespace is None:
            from ..utils import env as env_cfg

            namespace = env_cfg.job_kv_prefix()
        self.namespace = namespace

    def _path(self, suffix: str) -> str:
        return f"/{self.namespace}{suffix}" if self.namespace \
            else f"/{suffix}"

    def _conn(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.addr, self.port, timeout=10.0)

    def _retry(self, fn, what: str):
        """KV requests retry transient transport failures (refused while
        the server restarts mid-elastic-reset, reset, timeout) with
        exponential backoff + jitter; HTTP-level rejections (403 etc.)
        are NOT transport failures and propagate immediately. Per-attempt
        noise policy lives in call_with_retry: first and final failures
        log at WARNING, the rest only bump
        horovod_retry_attempts_total."""
        counter = _request_counter()

        def counted():
            counter.inc()
            return fn()

        return call_with_retry(
            counted, what,
            retry_on=(OSError, http.client.HTTPException),
        )

    def _headers(self, method: str, path: str, body: bytes = b"") -> dict:
        if self.secret_key is None:
            return {}
        from ..runner.rendezvous_server import sign_request

        digest, ts = sign_request(self.secret_key, method, path, body)
        return {"X-Horovod-Digest": digest, "X-Horovod-Timestamp": ts}

    def put(self, scope: str, key: str, value: bytes):
        def _put():
            c = self._conn()
            path = self._path(f"{scope}/{key}")
            try:
                c.request("PUT", path, body=value,
                          headers=self._headers("PUT", path, value))
                r = c.getresponse()
                r.read()
                if r.status != 200:
                    raise RuntimeError(f"rendezvous PUT failed: {r.status}")
            finally:
                c.close()

        self._retry(_put, f"rendezvous PUT {scope}/{key}")

    def get(self, scope: str, key: str) -> Optional[bytes]:
        def _get():
            c = self._conn()
            path = self._path(f"{scope}/{key}")
            try:
                c.request("GET", path, headers=self._headers("GET", path))
                r = c.getresponse()
                body = r.read()
                if r.status == 200:
                    return body
                if r.status == 403:
                    raise PermissionError(
                        "rendezvous rejected request: "
                        + (r.getheader("X-Horovod-Reject-Reason")
                           or "bad or missing HOROVOD_SECRET_KEY digest")
                    )
                return None
            finally:
                c.close()

        return self._retry(_get, f"rendezvous GET {scope}/{key}")

    def wait_get(self, scope: str, key: str) -> bytes:
        """Poll until the key exists (peers registering). One WARNING
        when the wait turns long (a peer is slow to register — the
        bootstrap-time analogue of a stall warning), not one per poll."""
        deadline = time.monotonic() + self.timeout
        warn_at: Optional[float] = time.monotonic() + min(self.timeout / 2, 15.0)
        while True:
            v = self.get(scope, key)
            if v is not None:
                return v
            now = time.monotonic()
            if warn_at is not None and now > warn_at:
                logger.warning(
                    "still waiting for rendezvous key %s/%s after %.0fs "
                    "(peer slow to register?)", scope, key, now - (deadline - self.timeout),
                )
                warn_at = None
            if now > deadline:
                raise TimeoutError(f"rendezvous key {scope}/{key} never appeared")
            time.sleep(0.05)

    def delete(self, scope: str):
        # Routed through the same retry/backoff path as put/get: this
        # was the one KV op that bypassed _retry, so a single refused
        # connection during elastic reset churn raised raw OSError
        # through the public API instead of being absorbed.
        def _delete():
            c = self._conn()
            path = self._path(f"{scope}")
            try:
                c.request("DELETE", path,
                          headers=self._headers("DELETE", path))
                c.getresponse().read()
            finally:
                c.close()

        self._retry(_delete, f"rendezvous DELETE {scope}")
