"""HTTP KV rendezvous client (worker side).

(ref: horovod/runner/http/http_client.py:17-45 read_data_from_kvstore /
put_data_into_kvstore; the C++ consumer is gloo_context.cc:70-151.)
"""
from __future__ import annotations

import http.client
import time
from typing import Optional


class RendezvousClient:
    def __init__(self, addr: str, port: int, timeout: float = 60.0):
        self.addr = addr
        self.port = port
        self.timeout = timeout

    def _conn(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.addr, self.port, timeout=10.0)

    def put(self, scope: str, key: str, value: bytes):
        c = self._conn()
        try:
            c.request("PUT", f"/{scope}/{key}", body=value)
            r = c.getresponse()
            r.read()
            if r.status != 200:
                raise RuntimeError(f"rendezvous PUT failed: {r.status}")
        finally:
            c.close()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        c = self._conn()
        try:
            c.request("GET", f"/{scope}/{key}")
            r = c.getresponse()
            body = r.read()
            if r.status == 200:
                return body
            return None
        finally:
            c.close()

    def wait_get(self, scope: str, key: str) -> bytes:
        """Poll until the key exists (peers registering)."""
        deadline = time.monotonic() + self.timeout
        while True:
            v = self.get(scope, key)
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise TimeoutError(f"rendezvous key {scope}/{key} never appeared")
            time.sleep(0.05)

    def delete(self, scope: str):
        c = self._conn()
        try:
            c.request("DELETE", f"/{scope}")
            c.getresponse().read()
        finally:
            c.close()
