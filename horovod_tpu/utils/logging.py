"""Leveled logging (ref: horovod/common/logging.{h,cc} — glog-style levels
selected by HOROVOD_LOG_LEVEL, timestamps toggled by HOROVOD_LOG_TIMESTAMP)."""
from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_logger = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        _logger = logging.getLogger("horovod_tpu")
        level = os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower()
        _logger.setLevel(_LEVELS.get(level, logging.WARNING))
        if not _logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            if os.environ.get("HOROVOD_LOG_TIMESTAMP"):
                fmt = "[%(asctime)s %(levelname)s %(name)s] %(message)s"
            else:
                fmt = "[%(levelname)s %(name)s] %(message)s"
            h.setFormatter(logging.Formatter(fmt))
            _logger.addHandler(h)
        _logger.propagate = False
    return _logger
