"""Environment-variable config knobs.

The reference funnels all runtime configuration through HOROVOD_* env vars
(ref: horovod/common/common.h:64-90, operations.cc:416-513,
horovod/runner/common/util/config_parser.py). We honor the same names so
reference users' launch scripts keep working, with HVD_TPU_* accepted as
an alias prefix.
"""
from __future__ import annotations

import os
from typing import Optional

# Knob names (ref: horovod/common/common.h:64-90)
FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
CYCLE_TIME = "HOROVOD_CYCLE_TIME"
CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
# Cross-host schedule of the two-level hierarchical allreduce
# (docs/running.md): "slice" — every local rank drives its own
# cross-host ring on its owned slice (parallel inter-host streams);
# "leader" — one leader per host gathers the host-reduced vector over
# the intra-host transport and runs a single segmented inter-host ring
# (the NCCL-hierarchical shape; one stream per host pair); "auto"
# (default) — leader when the intra-host data plane is shared memory
# on every host (agreed collectively at engine init), slice otherwise.
HIERARCHICAL_MODE = "HOROVOD_HIERARCHICAL_MODE"
AUTOTUNE = "HOROVOD_AUTOTUNE"
AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
TIMELINE = "HOROVOD_TIMELINE"
TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
STALL_CHECK_TIME = "HOROVOD_STALL_CHECK_TIME_SECONDS"
STALL_SHUTDOWN_TIME = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
LOG_LEVEL = "HOROVOD_LOG_LEVEL"
CONTROLLER = "HOROVOD_CONTROLLER"
CPU_OPERATIONS = "HOROVOD_CPU_OPERATIONS"
ADASUM_NUM_STREAMS = "HOROVOD_NUM_NCCL_STREAMS"

# Rank topology env set by the launcher (ref: gloo_run.py:65-198)
RANK = "HOROVOD_RANK"
SIZE = "HOROVOD_SIZE"
LOCAL_RANK = "HOROVOD_LOCAL_RANK"
LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
CROSS_RANK = "HOROVOD_CROSS_RANK"
CROSS_SIZE = "HOROVOD_CROSS_SIZE"
RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOSTNAME = "HOROVOD_HOSTNAME"
SECRET_KEY = "HOROVOD_SECRET_KEY"
ELASTIC = "HOROVOD_ELASTIC"
# Rendezvous scope for the TCP full-mesh bootstrap; the elastic driver
# bumps it per topology epoch so re-initializing workers never collide
# with stale peer addresses.
MESH_SCOPE = "HOROVOD_MESH_SCOPE"

# -- fault-tolerance knobs (docs/fault_tolerance.md) -------------------
# Bound on any single socket send/recv on the TCP data plane; 0 (the
# default) means unbounded, but dead-peer FINs are still detected
# because the recv loop polls instead of blocking forever.
TCP_TIMEOUT = "HOROVOD_TCP_TIMEOUT_SECONDS"
# Poll interval of the bounded recv loop (the heartbeat granularity).
TCP_POLL = "HOROVOD_TCP_POLL_SECONDS"
# Connect-time retry budget against peers and the rendezvous KV store:
# attempts, base backoff (doubles per attempt, +/- 50% jitter), cap.
CONNECT_ATTEMPTS = "HOROVOD_CONNECT_ATTEMPTS"
CONNECT_BACKOFF = "HOROVOD_CONNECT_BACKOFF_SECONDS"
CONNECT_BACKOFF_CAP = "HOROVOD_CONNECT_BACKOFF_CAP_SECONDS"

# -- transport selection knobs (docs/running.md "Transports") ----------
# Which data-plane transport moves collective payloads between ranks:
#   auto (default) — shm where peers are co-located, tcp otherwise:
#          co-located ranks engage the shared-memory overlay
#          automatically, remote peers stay on TCP. (Flipped from tcp
#          after the shm plane soaked in CI; pin tcp to reproduce the
#          old behavior or to assert tcp-only byte accounting.)
#   tcp  — every byte rides the TCP mesh sockets, co-located ranks
#          included (loopback through the kernel).
#   shm  — co-located ranks (same host, agreed via the rendezvous KV
#          locality rows) exchange data-channel frames over mmap'd
#          shared-memory ring buffers; remote peers stay on TCP —
#          operationally identical to auto (shm degrades to tcp for
#          remote pairs anyway); spells out the intent.
# Control-plane and heartbeat frames ALWAYS ride the TCP mesh — the
# socket FIN/RST is what makes dead-peer detection bounded, and a
# wedged peer's shm ring going quiet is attributed by the same
# heartbeat verdict. The knob is read per send/recv, so benchmarks may
# flip tcp<->shm between barrier-separated rounds; establishment (ring
# creation) happens once at init and only when the LAUNCH value was
# shm/auto.
TRANSPORT = "HOROVOD_TRANSPORT"
# Per-direction shared-memory ring capacity in bytes. Frames larger
# than the ring stream through it (bounded-buffer pipe semantics), so
# this bounds memory, not message size.
SHM_RING_BYTES = "HOROVOD_SHM_RING_BYTES"
# Directory for the ring files; default /dev/shm when present (true
# page-cache-backed tmpfs), else the system temp dir.
SHM_DIR = "HOROVOD_SHM_DIR"
# Per-rank slot size of the intra-host arena (the fully-co-located
# allreduce path): tensors up to this size move in one chunk; larger
# ones stream through in slot-sized passes. Memory cost per arena is
# (local_size + 1) x slot_bytes of tmpfs, materialized lazily per
# executor channel.
SHM_SLOT_BYTES = "HOROVOD_SHM_SLOT_BYTES"
# Intra-host legs of the leader-mode hierarchical allreduce: "auto"
# (default) routes them through the per-HOST shared-memory arena when
# every host's local group is covered by one (a collectively AND-agreed
# capability bit — a host that cannot map its arena degrades the whole
# schedule to the per-pair shm rings consistently); "off" pins the
# per-pair rings. Read per call like HOROVOD_TRANSPORT, so paired
# benchmarks can flip the legs between barrier-separated rounds.
HIER_ARENA = "HOROVOD_HIER_ARENA"

DEFAULT_SHM_RING_BYTES = 4 << 20
DEFAULT_SHM_SLOT_BYTES = 16 << 20

# -- liveness plane knobs (docs/fault_tolerance.md) --------------------
# Cadence of the always-on heartbeat plane: workers beat the coordinator
# and the coordinator acks every worker on this interval, over the
# existing control sockets (a dedicated frame tag, so heartbeats cost
# nothing on the data path). 0 disables the liveness plane entirely.
HEARTBEAT_INTERVAL = "HOROVOD_HEARTBEAT_INTERVAL_SECONDS"
# A rank silent (no heartbeat AND no frames of any kind) for more than
# miss_limit x interval is declared dead: the coordinator broadcasts a
# tensor-less ERROR response naming it (the stall-abort path), so
# detection is bounded even on an idle mesh with
# HOROVOD_TCP_TIMEOUT_SECONDS=0. Workers symmetrically declare the
# coordinator dead on missing acks. 0 disables dead declarations.
HEARTBEAT_MISS_LIMIT = "HOROVOD_HEARTBEAT_MISS_LIMIT"
# Elastic driver: a reset barrier slot with no verdict (READY/SUCCESS/
# FAILURE) after this many seconds is evicted — the worker is killed and
# recorded as failed — so the barrier ALWAYS fires and survivors
# re-mesh. 0 disables the watchdog (the pre-liveness behavior: a wedged
# worker parks every survivor forever).
ELASTIC_READY_TIMEOUT = "HOROVOD_ELASTIC_READY_TIMEOUT"
# Worker-side bound on waiting for a new topology epoch during an
# elastic reset (refresh_topology_from_rendezvous).
ELASTIC_RESET_TIMEOUT = "HOROVOD_ELASTIC_RESET_TIMEOUT"
# Host blacklist cooldown: a host's FIRST failure blacklists it for this
# many seconds (transient flake — the host gets another chance); a
# repeat failure blacklists it permanently. 0 = permanent on the first
# failure (the pre-cooldown behavior).
BLACKLIST_COOLDOWN = "HOROVOD_BLACKLIST_COOLDOWN_SECONDS"

# -- preemption / drain knobs (docs/fault_tolerance.md "Announced
#    preemption") ------------------------------------------------------
# Grace window a worker has between the preemption notice (the signal
# named by HOROVOD_PREEMPT_SIGNAL, SIGTERM by default) and its forced
# exit. A drain-aware loop (hvd.elastic.run) uses the window to force a
# final checkpoint, release the goodput stamp and publish the drain
# notice; the deadline timer then hard-exits with code 0 so a stuck
# drain can never outlive the platform's own kill.
DRAIN_GRACE_SECONDS = "HOROVOD_DRAIN_GRACE_SECONDS"
# Signal treated as the preemption notice (name like "SIGTERM"/"TERM"
# or a number). Spot/multi-tenant platforms differ; the drain handler,
# the launcher's teardown path and the fault injector's `preempt`
# action all send/catch this one signal.
PREEMPT_SIGNAL = "HOROVOD_PREEMPT_SIGNAL"
# Cadence of the goodput-driven elasticity controller in the elastic
# runner (runner/elastic/controller.py): every interval it reads the
# goodput stamp, the fleet alert verdicts and rendezvous liveness and
# decides scale-up / scale-down / hold. 0 disables the controller.
CONTROLLER_INTERVAL_SECONDS = "HOROVOD_CONTROLLER_INTERVAL_SECONDS"
# Job identity for sharing ONE rendezvous server between jobs (a
# trainer and a server on the same fleet): when set, every KV key the
# client and driver touch is prefixed with `jobs/<name>/`, so two jobs
# never collide, and the prefix doubles as the registration the
# server's capacity arbitration (HOROVOD_FLEET_SLOTS) grants slots
# against. Empty (default) = no namespace, the single-job layout.
JOB_NAME = "HOROVOD_JOB_NAME"
# Total fleet slots a SHARED rendezvous server arbitrates between jobs
# (runner/rendezvous_server.py arbitrate_capacity): each job PUTs its
# want under jobs/<name>/capacity/want and reads its max-min-fair grant
# back from jobs/<name>/capacity/grant. 0 (default) disables
# arbitration — the server is a plain KV store.
FLEET_SLOTS = "HOROVOD_FLEET_SLOTS"

DEFAULT_HEARTBEAT_INTERVAL_SECONDS = 5.0
DEFAULT_HEARTBEAT_MISS_LIMIT = 6
DEFAULT_ELASTIC_READY_TIMEOUT = 180.0
DEFAULT_ELASTIC_RESET_TIMEOUT = 600.0
DEFAULT_BLACKLIST_COOLDOWN_SECONDS = 600.0
DEFAULT_DRAIN_GRACE_SECONDS = 30.0
DEFAULT_PREEMPT_SIGNAL = "SIGTERM"
DEFAULT_CONTROLLER_INTERVAL_SECONDS = 30.0

# -- pipelined execution knobs (docs/running.md) -----------------------
# Number of concurrent executor channels the coordinator round-robins
# non-fence responses over. Each rank executes a channel's responses in
# FIFO order on a dedicated worker thread, so independent collectives
# overlap while same-channel ordering (the anti-deadlock invariant)
# holds. 1 = fully serial execution (still overlapped with negotiation).
# Only the coordinator's value matters for assignment — workers follow
# the channel id carried in the Response wire message.
NUM_CHANNELS = "HOROVOD_NUM_CHANNELS"
# Backpressure bound: at most this many responses may be dispatched-but-
# unfinished across all channels before the background loop stops
# handing out more (and thus stops pulling new negotiation rounds).
MAX_INFLIGHT = "HOROVOD_MAX_INFLIGHT_RESPONSES"
# Channel assignment policy: "size" (default) reserves the highest
# channel as a latency lane for small responses (<= LATENCY_CHANNEL
# bytes) and round-robins bulk responses over the rest, so a metrics/
# loss scalar is never head-of-line blocked behind a streaming gradient
# (the multi-stream split Horovod and PyTorch DDP both converge on);
# "rr" round-robins everything blindly.
CHANNEL_POLICY = "HOROVOD_CHANNEL_POLICY"
LATENCY_CHANNEL_BYTES = "HOROVOD_LATENCY_CHANNEL_BYTES"
# Event-driven cycles: 1 (default) replaces the unconditional cycle
# sleep with a wait that wakes the moment a tensor is enqueued, turning
# HOROVOD_CYCLE_TIME into a max-coalescing delay instead of a latency
# floor. 0 restores the fixed-sleep schedule (the pre-pipelining
# baseline, kept for A/B latency measurement).
CYCLE_EVENT = "HOROVOD_CYCLE_EVENT_DRIVEN"

DEFAULT_NUM_CHANNELS = 2
MAX_CHANNELS = 16
DEFAULT_LATENCY_CHANNEL_BYTES = 65536

# -- wire-compression knobs (docs/running.md "Wire compression") -------
# On-wire codec policy for the collective data plane: none (default —
# every byte ships full-width), bf16 / fp16 (that codec for eligible
# fp32 allreduce responses), auto (bf16 — the TPU-native pick: same
# byte savings as fp16 with the full fp32 exponent range). Read per
# negotiation cycle ON THE COORDINATOR only: the chosen codec id rides
# the Response wire message next to the channel id, so workers follow
# rank 0's policy and the choice is collectively agreed and
# cache-replay-stable by construction (flipping the env mid-run on a
# worker changes nothing; flipping it on rank 0 affects newly
# negotiated responses only — cached ones keep their codec).
WIRE_COMPRESSION = "HOROVOD_WIRE_COMPRESSION"
# Responses below this negotiated payload size ship full-width even
# when a codec is configured: encode/decode overhead beats the byte
# savings on small frames (the latency channel's int8 opt-in below is
# the deliberate exception).
WIRE_COMPRESSION_MIN_BYTES = "HOROVOD_WIRE_COMPRESSION_MIN_BYTES"
# Opt-in: responses riding the latency channel (the size policy's
# highest lane) additionally quantize to int8-with-scale (4x fewer
# bytes) when a non-none codec mode is active. Off by default — int8
# is coarse; error feedback recovers the mean but per-step noise is
# real.
WIRE_COMPRESSION_INT8 = "HOROVOD_WIRE_COMPRESSION_INT8"
# Codec/wire overlap in the segmented ring (docs/running.md "Wire
# compression"): 1 (default) encodes segment k+1 and decodes-reduces
# segment k-1 on bounded single-worker stages while segment k is on
# the wire, hiding the cast passes behind wire time. 0 restores the
# serial schedule (encode whole chunk, then recv+decode inline) — the
# wire bytes and results are bitwise identical either way, so the knob
# is a purely local A/B switch.
RING_CODEC_OVERLAP = "HOROVOD_RING_CODEC_OVERLAP"
# ZeRO sharded optimizer state (docs/running.md "ZeRO sharded optimizer
# state"): the default stage `DistributedOptimizer(zero=None)` resolves
# to. 0 = replicated moments on every data rank (off), 1/2 = shard the
# optimizer state over the resolved data axis (stage 2 additionally
# documents the reduce-scatter gradient lowering; the state layout is
# identical). Read at wrapper-construction time — launcher-propagated,
# so collectively consistent.
ZERO_SHARDING = "HOROVOD_ZERO_SHARDING"

DEFAULT_WIRE_COMPRESSION_MIN_BYTES = 65536

# -- tracing knobs (docs/tracing.md) -----------------------------------
# Merged Perfetto/Chrome trace file rank 0 writes at shutdown (every
# rank writes its own when the path contains `{rank}`). Unset = no file
# (the /trace endpoint still serves the live merged view).
TRACE_FILE = "HOROVOD_TRACE_FILE"
# Directory for failure post-mortems: on an engine latch every rank
# dumps its flight recorder here (flight_rank<r>.json) and the
# coordinator stitches them with the health verdict into
# postmortem.json. Unset = no dumps.
TRACE_DIR = "HOROVOD_TRACE_DIR"
# Capacity of the always-on in-memory flight recorder (events per
# rank). The ring overwrites oldest events (counted in
# horovod_trace_events_dropped_total{source="recorder"}); 0 disables
# the tracing plane entirely (spans become no-ops).
TRACE_BUFFER = "HOROVOD_TRACE_BUFFER_EVENTS"
# Auto-dump the flight recorder to HOROVOD_TRACE_DIR when the engine
# latches a fatal error (default on; the dump is a no-op without a
# trace dir).
TRACE_DUMP_ON_ERROR = "HOROVOD_TRACE_DUMP_ON_ERROR"

DEFAULT_TRACE_BUFFER_EVENTS = 16384

# -- durability / checkpoint knobs (docs/checkpoint.md) ----------------
# Directory for sharded checkpoints (shared storage across all ranks —
# a gcsfuse mount on TPU-VMs, NFS, or a local dir on one box). Unset =
# the durability plane is off: no snapshots, no writer thread.
CHECKPOINT_DIR = "HOROVOD_CHECKPOINT_DIR"
# Checkpoint every N state commits (the elastic loop's `state.commit()`
# is the trigger point). 0 disables periodic checkpoints even with a
# directory set (explicit `CheckpointManager.save()` still works).
CHECKPOINT_INTERVAL = "HOROVOD_CHECKPOINT_INTERVAL_STEPS"
# Complete checkpoints retained; older manifests AND their shard dirs
# are garbage-collected by the coordinator after each commit.
CHECKPOINT_KEEP = "HOROVOD_CHECKPOINT_KEEP"
# Coordinator-side bound on collecting per-rank durability acks before
# a manifest commit is abandoned (counted as a failure; shards from the
# incomplete checkpoint are GC'd later, never referenced).
CHECKPOINT_COMMIT_TIMEOUT = "HOROVOD_CHECKPOINT_COMMIT_TIMEOUT_SECONDS"
# fsync shard + manifest writes (survive power loss, not just process
# death). Default on; turn off to trade durability for write latency.
CHECKPOINT_FSYNC = "HOROVOD_CHECKPOINT_FSYNC"

DEFAULT_CHECKPOINT_INTERVAL_STEPS = 10
DEFAULT_CHECKPOINT_KEEP = 3
DEFAULT_CHECKPOINT_COMMIT_TIMEOUT = 120.0

# -- serving plane knobs (docs/serving.md) -----------------------------
# Port of the rank-0 HTTP front door (POST /v1/infer). Empty/unset =
# the serving plane never opens a socket; 0 = ephemeral port (tests
# read it back from the frontend object).
SERVING_PORT = "HOROVOD_SERVING_PORT"
# Bind address of the front door. Loopback by default for the same
# reason as HOROVOD_METRICS_ADDR: the endpoint is unauthenticated, so
# network exposure is the explicit opt-in.
SERVING_ADDR = "HOROVOD_SERVING_ADDR"
# Continuous-batching caps: a dispatch closes when it holds MAX_BATCH
# requests, when the summed per-request token budget reaches
# MAX_BATCH_TOKENS, or when the oldest admitted request has waited
# MAX_DELAY_MS — whichever comes first. Like HOROVOD_CYCLE_TIME the
# delay is a max-coalescing bound, never a latency floor: the batcher
# wakes on enqueue and a full batch dispatches immediately.
SERVING_MAX_BATCH = "HOROVOD_SERVING_MAX_BATCH"
SERVING_MAX_BATCH_TOKENS = "HOROVOD_SERVING_MAX_BATCH_TOKENS"
SERVING_MAX_DELAY_MS = "HOROVOD_SERVING_MAX_DELAY_MS"
# Bounded admission queue: requests arriving while QUEUE_DEPTH are
# already admitted are rejected with HTTP 429 (backpressure — the
# client retries; an unbounded queue just converts overload into
# timeouts for everyone).
SERVING_QUEUE_DEPTH = "HOROVOD_SERVING_QUEUE_DEPTH"
# Per-request deadline: admitted requests still undispatched past it
# are dropped BEFORE dispatch (counted, never forwarded) and the
# client gets 504; a client may lower (never raise) it per request.
SERVING_REQUEST_TIMEOUT = "HOROVOD_SERVING_REQUEST_TIMEOUT_SECONDS"
# How often the serving coordinator polls the checkpoint manifest
# store (HOROVOD_CHECKPOINT_DIR; disk is the truth — the KV
# `ckpt/latest` row is best-effort and never gates discovery) for
# newly-committed weights to hot-swap. 0 disables the watch.
SERVING_WEIGHT_REFRESH = "HOROVOD_SERVING_WEIGHT_REFRESH_SECONDS"
# Redundant front doors (docs/serving.md "Redundant front doors"):
# the first N live ranks each open the HTTP frontend. Exactly one —
# the lowest live rank — is the ACTIVE door that drives rounds; the
# others admit against bounded-queue leases and forward through the
# round protocol. The admission budget (SERVING_QUEUE_DEPTH) is split
# across doors, agreed via the rendezvous-KV door row, never a lock.
SERVING_DOORS = "HOROVOD_SERVING_DOORS"
# Master switch for chunked streaming responses ({"stream": true}
# requests). Off = streaming requests are answered unary (the final
# chunk only); unary JSON stays the default either way.
SERVING_STREAM = "HOROVOD_SERVING_STREAM"
# Serving-autoscaler decision cadence. 0 (the default) disables the
# policy loop; >0 makes the coordinator consume the serving/load KV
# row every interval and grow/shrink the mesh via subset re-mesh
# (cooldown = 3x the interval, PR 16 controller shape).
SERVING_AUTOSCALE_INTERVAL = "HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS"

DEFAULT_SERVING_MAX_BATCH = 32
DEFAULT_SERVING_MAX_BATCH_TOKENS = 16384
DEFAULT_SERVING_MAX_DELAY_MS = 5.0
DEFAULT_SERVING_QUEUE_DEPTH = 256
DEFAULT_SERVING_REQUEST_TIMEOUT = 30.0
DEFAULT_SERVING_WEIGHT_REFRESH = 10.0
DEFAULT_SERVING_DOORS = 1

# -- goodput plane knobs (docs/goodput.md) -----------------------------
# Master switch for the step-accounting ledger (step demarcation,
# exposed-comm attribution, restart badput). Default on: every hook is
# a float add; turn off to prove the <2% hot-path bar or to silence
# the ledger entirely.
GOODPUT = "HOROVOD_GOODPUT"
# Directory for the durable ledger stamp (goodput.json) that carries
# job start / step cursor / cumulative badput across process
# lifetimes, so a kill-all restart's downtime and replayed steps are
# counted. Defaults to HOROVOD_CHECKPOINT_DIR (the stamp lives next to
# the checkpoints it accounts for); empty when neither is set = no
# durable stamps (per-lifetime accounting only).
GOODPUT_DIR = "HOROVOD_GOODPUT_DIR"
# Rate limit on stamp persistence (written at commit boundaries).
# 0 (default) = stamp every commit — the stamp is a ~1KB unfsynced
# atomic write, far below step cost; raise it on very fast commit
# loops or slow shared stores.
GOODPUT_STAMP_SECONDS = "HOROVOD_GOODPUT_STAMP_SECONDS"
# Declared flops of ONE training step (per rank). >0 adds achieved
# FLOP/s to the /goodput view; with PEAK_FLOPS also set, MFU.
STEP_FLOPS = "HOROVOD_STEP_FLOPS"
# Peak per-rank FLOP/s of the hardware, for the MFU ratio.
GOODPUT_PEAK_FLOPS = "HOROVOD_GOODPUT_PEAK_FLOPS"

# -- health plane knobs (docs/health.md) -------------------------------
# Cadence of the on-box metrics sampler: a daemon thread snapshots the
# registry every this-many seconds into a bounded in-memory ring
# (common/timeseries.py) — the history behind /timeseries, the alert
# engine and the post-mortem series dump. Reuses the existing
# snapshot() machinery, so the hot path pays nothing. <= 0 disables
# the health plane.
METRICS_SAMPLE_SECONDS = "HOROVOD_METRICS_SAMPLE_SECONDS"
# Ring capacity in samples (default 360 = one hour at the 10 s default
# cadence). Bounded memory like the flight-recorder ring; overwrites
# are counted in horovod_timeseries_samples_dropped_total. 0 disables
# the health plane.
METRICS_HISTORY_SAMPLES = "HOROVOD_METRICS_HISTORY_SAMPLES"
# Alert rule list: enable/disable/override the built-in default rules
# (common/alerts.py; docs/health.md "Rule grammar"). Comma-separated
# tokens: `-name` disables a default, `name` keeps it, and
# `name:param=value:param=value` overrides its parameters; `none`/`off`
# disables every rule. Empty (the default) = all defaults armed.
ALERT_RULES = "HOROVOD_ALERT_RULES"
# Serving latency SLO: fires the serving_p99_slo burn-rate alert when
# the windowed p99 of horovod_serving_request_seconds exceeds this
# target in BOTH the fast and slow windows (multi-window burn-rate, so
# a single spike never pages). 0 (default) disarms the rule.
SERVING_SLO_P99_MS = "HOROVOD_SERVING_SLO_P99_MS"

DEFAULT_METRICS_SAMPLE_SECONDS = 10.0
DEFAULT_METRICS_HISTORY_SAMPLES = 360

# -- events plane knobs (docs/events.md) -------------------------------
# Capacity of the per-process lifecycle event ring (common/events.py).
# The ring overwrites oldest events (counted in
# horovod_events_dropped_total); 0 disables the events plane entirely
# (emit becomes a no-op, no spool thread).
EVENTS_BUFFER = "HOROVOD_EVENTS_BUFFER"
# Directory for the durable per-rank JSONL event journal
# (events_rank<r>.jsonl + an atomically written clock-anchor sidecar).
# Unset (the default) = ring only, no files, no writer thread.
EVENTS_DIR = "HOROVOD_EVENTS_DIR"
# Flush cadence of the journal writer thread. Events are queued off the
# hot path and appended+flushed every this-many seconds; lower = less
# loss on a hard kill, higher = fewer write() calls.
EVENTS_SPOOL_SECONDS = "HOROVOD_EVENTS_SPOOL_SECONDS"

DEFAULT_EVENTS_BUFFER = 4096
DEFAULT_EVENTS_SPOOL_SECONDS = 1.0

# -- telemetry knobs (docs/metrics.md) ---------------------------------
# Serve Prometheus text at /metrics and live job state at /status from a
# daemon thread on rank 0. Unset/empty = disabled; 0 = ephemeral port.
METRICS_PORT = "HOROVOD_METRICS_PORT"
# Bind address for the metrics endpoint. Default 127.0.0.1: the
# endpoint is unauthenticated (/status exposes tensor names and
# per-rank state), so network exposure for remote Prometheus scrapers
# is the explicit opt-in (HOROVOD_METRICS_ADDR=0.0.0.0).
METRICS_ADDR = "HOROVOD_METRICS_ADDR"
# Periodic JSON snapshot dump; `{rank}` in the path expands per rank.
METRICS_FILE = "HOROVOD_METRICS_FILE"
METRICS_FILE_INTERVAL = "HOROVOD_METRICS_FILE_INTERVAL"
# How often each rank piggybacks its scalar snapshot on the coordinator
# control plane for rank 0's fleet view; 0 disables aggregation.
METRICS_SYNC_SECONDS = "HOROVOD_METRICS_SYNC_SECONDS"

DEFAULT_METRICS_SYNC_SECONDS = 3.0

DEFAULT_TCP_POLL_SECONDS = 1.0
DEFAULT_CONNECT_ATTEMPTS = 5
DEFAULT_CONNECT_BACKOFF_SECONDS = 0.1
DEFAULT_CONNECT_BACKOFF_CAP_SECONDS = 2.0

DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024  # ref: operations.cc:432
DEFAULT_CYCLE_TIME_MS = 5.0  # ref: operations.cc:442
DEFAULT_CACHE_CAPACITY = 1024  # ref: global_state.h:88
DEFAULT_STALL_WARNING_SECONDS = 60.0  # ref: stall_inspector.h


def _get(name: str) -> Optional[str]:
    v = os.environ.get(name)
    if v is None:
        v = os.environ.get(name.replace("HOROVOD_", "HVD_TPU_", 1))
    return v


def get_int(name: str, default: int) -> int:
    v = _get(name)
    return int(v) if v not in (None, "") else default


def get_float(name: str, default: float) -> float:
    v = _get(name)
    return float(v) if v not in (None, "") else default


def get_str(name: str, default: str = "") -> str:
    v = _get(name)
    return v if v is not None else default


def get_bool(name: str, default: bool = False) -> bool:
    v = _get(name)
    if v in (None, ""):
        return default
    return v.lower() not in ("0", "false", "no", "off")


def fusion_threshold_bytes() -> int:
    # HOROVOD_FUSION_THRESHOLD is in bytes (ref: operations.cc:432-440)
    return get_int(FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES)


def cycle_time_ms() -> float:
    return get_float(CYCLE_TIME, DEFAULT_CYCLE_TIME_MS)


def cache_capacity() -> int:
    return get_int(CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY)


def cache_enabled() -> bool:
    """HOROVOD_CACHE_CAPACITY=0 disables the response cache
    (ref: operations.cc:455-462)."""
    return cache_capacity() != 0


def tcp_timeout_seconds() -> float:
    """0 = unbounded (the recv loop still polls for dead-peer FINs).
    Also the generic transport idle bound: the shm rings apply it to
    ring-full send stalls and empty-ring recv waits the same way."""
    return get_float(TCP_TIMEOUT, 0.0)


def transport_mode() -> str:
    """HOROVOD_TRANSPORT, normalized to tcp|shm|auto (unknown values
    fall back to the default — never crash the data plane over a typo;
    the value is logged at establishment). Default `auto`: co-located
    ranks ride the shm overlay, remote peers ride tcp. Read per call so
    paired benchmarks can flip the ROUTE between barrier-separated
    rounds."""
    v = get_str(TRANSPORT, "auto").lower()
    return v if v in ("tcp", "shm", "auto") else "auto"


def shm_ring_bytes() -> int:
    """Per-direction shm ring capacity; floor 64KB so tiny settings
    cannot degenerate into a byte-at-a-time pipe."""
    return max(get_int(SHM_RING_BYTES, DEFAULT_SHM_RING_BYTES), 1 << 16)


def shm_slot_bytes() -> int:
    """Arena per-rank slot capacity; floor 64KB."""
    return max(get_int(SHM_SLOT_BYTES, DEFAULT_SHM_SLOT_BYTES), 1 << 16)


def shm_dir() -> str:
    d = get_str(SHM_DIR, "")
    if d:
        return d
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    import tempfile

    return tempfile.gettempdir()


def tcp_poll_seconds() -> float:
    poll = get_float(TCP_POLL, DEFAULT_TCP_POLL_SECONDS)
    timeout = tcp_timeout_seconds()
    if timeout > 0:
        # The poll must subdivide the deadline or a single blocking
        # recv() could overshoot it.
        poll = min(poll, max(timeout / 4.0, 0.01))
    return max(poll, 0.01)


def heartbeat_interval_seconds() -> float:
    """Heartbeat cadence; 0 disables the liveness plane."""
    return get_float(HEARTBEAT_INTERVAL, DEFAULT_HEARTBEAT_INTERVAL_SECONDS)


def heartbeat_miss_limit() -> int:
    """Silent intervals before a dead declaration; 0 disables."""
    return get_int(HEARTBEAT_MISS_LIMIT, DEFAULT_HEARTBEAT_MISS_LIMIT)


def heartbeat_enabled() -> bool:
    return heartbeat_interval_seconds() > 0 and heartbeat_miss_limit() > 0


def elastic_ready_timeout() -> float:
    """Reset-barrier verdict deadline; 0 disables eviction."""
    return get_float(ELASTIC_READY_TIMEOUT, DEFAULT_ELASTIC_READY_TIMEOUT)


def elastic_reset_timeout() -> float:
    return get_float(ELASTIC_RESET_TIMEOUT, DEFAULT_ELASTIC_RESET_TIMEOUT)


def blacklist_cooldown_seconds() -> float:
    """First-failure blacklist duration; 0 = permanent immediately."""
    return get_float(BLACKLIST_COOLDOWN, DEFAULT_BLACKLIST_COOLDOWN_SECONDS)


def drain_grace_seconds() -> float:
    """Preemption-notice grace window (floor 0). A bogus value falls to
    the default — a typo in an operator override must never turn the
    drain deadline off or make it negative."""
    try:
        return max(get_float(DRAIN_GRACE_SECONDS,
                             DEFAULT_DRAIN_GRACE_SECONDS), 0.0)
    except ValueError:
        return DEFAULT_DRAIN_GRACE_SECONDS


def preempt_signal() -> int:
    """HOROVOD_PREEMPT_SIGNAL as a signal number. Accepts a name with
    or without the SIG prefix ("SIGTERM", "term", "USR1") or a plain
    number; anything unrecognized falls back to SIGTERM — the drain
    handler and the sender MUST agree, and a typo that made them
    diverge would turn every intentional stop back into a hard kill."""
    import signal as _signal

    v = get_str(PREEMPT_SIGNAL, DEFAULT_PREEMPT_SIGNAL).strip()
    if not v:
        return _signal.SIGTERM
    try:
        return int(v)
    except ValueError:
        pass
    name = v.upper()
    if not name.startswith("SIG"):
        name = "SIG" + name
    sig = getattr(_signal, name, None)
    if isinstance(sig, _signal.Signals):
        return int(sig)
    return _signal.SIGTERM


def controller_interval_seconds() -> float:
    """Elasticity-controller cadence; 0 disables the controller. Bogus
    values fall to the default (a broken override must never silently
    disable the decision loop)."""
    try:
        return max(get_float(CONTROLLER_INTERVAL_SECONDS,
                             DEFAULT_CONTROLLER_INTERVAL_SECONDS), 0.0)
    except ValueError:
        return DEFAULT_CONTROLLER_INTERVAL_SECONDS


def job_name() -> str:
    """HOROVOD_JOB_NAME sanitized to [A-Za-z0-9._-] (the name becomes a
    KV key segment; a slash or whitespace would split or corrupt the
    namespace). A value with no valid characters falls to "" — the
    un-namespaced single-job layout."""
    import re as _re

    return _re.sub(r"[^A-Za-z0-9._-]", "", get_str(JOB_NAME, ""))


def job_kv_prefix() -> str:
    """The per-job KV key prefix ("jobs/<name>/", or "" when no job
    name is set). Clients and the elastic driver both apply it, so one
    rendezvous server can host a trainer and a server fleet without key
    collisions (docs/elastic.md "Sharing one rendezvous server")."""
    name = job_name()
    return f"jobs/{name}/" if name else ""


def fleet_slots() -> int:
    """Total slots the shared rendezvous server arbitrates between
    jobs; 0 (default, and the fallback for bogus values) disables
    capacity arbitration."""
    try:
        return max(get_int(FLEET_SLOTS, 0), 0)
    except ValueError:
        return 0


def num_channels() -> int:
    """Executor channels the coordinator round-robins responses over;
    clamped to [1, MAX_CHANNELS] (channel ids must stay below the
    reserved control-plane tag). Read per negotiation cycle on the
    coordinator so benchmarks can flip it between timed loops."""
    n = get_int(NUM_CHANNELS, DEFAULT_NUM_CHANNELS)
    return max(1, min(n, MAX_CHANNELS))


def max_inflight_responses() -> int:
    """Dispatched-but-unfinished response bound (backpressure window);
    defaults to 2 per channel. Always >= 1 or nothing would ever run."""
    return max(get_int(MAX_INFLIGHT, 2 * num_channels()), 1)


def channel_policy() -> str:
    """"size" (default) or "rr" — see CHANNEL_POLICY above. Coordinator-
    side only, like num_channels."""
    v = get_str(CHANNEL_POLICY, "size").lower()
    return v if v in ("size", "rr") else "size"


def latency_channel_bytes() -> int:
    """Responses at or below this byte count ride the latency lane
    under the size policy."""
    return get_int(LATENCY_CHANNEL_BYTES, DEFAULT_LATENCY_CHANNEL_BYTES)


def cycle_event_driven() -> bool:
    return get_bool(CYCLE_EVENT, True)


def wire_compression_mode() -> str:
    """HOROVOD_WIRE_COMPRESSION normalized to none|bf16|fp16|auto
    (unknown values fall back to none — a typo must never change what
    the data plane ships). Coordinator-side only, like num_channels:
    the assigned codec id rides the Response wire message."""
    v = get_str(WIRE_COMPRESSION, "none").lower()
    return v if v in ("none", "bf16", "fp16", "auto") else "none"


def wire_compression_min_bytes() -> int:
    """Smallest negotiated payload a codec engages on; floor 0."""
    return max(get_int(WIRE_COMPRESSION_MIN_BYTES,
                       DEFAULT_WIRE_COMPRESSION_MIN_BYTES), 0)


def wire_compression_int8() -> bool:
    """int8-with-scale on the latency channel (opt-in)."""
    return get_bool(WIRE_COMPRESSION_INT8, False)


def ring_codec_overlap() -> bool:
    """Pipelined codec/wire overlap in the segmented ring (default on).
    Purely local: flipping it never changes wire bytes or results."""
    return get_bool(RING_CODEC_OVERLAP, True)


def zero_sharding_default() -> int:
    """HOROVOD_ZERO_SHARDING normalized to 0|1|2 (bogus values fall
    back to 0 — a typo must never silently change the optimizer-state
    layout)."""
    try:
        v = get_int(ZERO_SHARDING, 0)
    except ValueError:
        return 0
    return v if v in (1, 2) else 0


def trace_buffer_events() -> int:
    """Flight-recorder ring capacity; 0 disables the tracing plane."""
    return max(get_int(TRACE_BUFFER, DEFAULT_TRACE_BUFFER_EVENTS), 0)


def trace_file() -> str:
    return get_str(TRACE_FILE, "")


def trace_dir() -> str:
    return get_str(TRACE_DIR, "")


def trace_dump_on_error() -> bool:
    return get_bool(TRACE_DUMP_ON_ERROR, True)


def events_buffer() -> int:
    """Lifecycle-event ring capacity; 0 disables the events plane.
    A bogus value falls back to the default (the plane must never be
    silently disabled by a typo)."""
    try:
        return max(int(_get(EVENTS_BUFFER) or DEFAULT_EVENTS_BUFFER), 0)
    except ValueError:
        return DEFAULT_EVENTS_BUFFER


def events_dir() -> str:
    """Durable JSONL journal directory; empty = ring only."""
    return get_str(EVENTS_DIR, "")


def events_spool_seconds() -> float:
    """Journal writer flush cadence; floor 0.05 s (a zero/bogus value
    must not spin the writer thread)."""
    try:
        v = float(_get(EVENTS_SPOOL_SECONDS) or DEFAULT_EVENTS_SPOOL_SECONDS)
    except ValueError:
        return DEFAULT_EVENTS_SPOOL_SECONDS
    return max(v, 0.05)


def checkpoint_dir() -> str:
    """Shared checkpoint directory; empty = durability plane off."""
    return get_str(CHECKPOINT_DIR, "")


def checkpoint_interval_steps() -> int:
    """Commits between checkpoints; 0 disables periodic snapshots."""
    return max(get_int(CHECKPOINT_INTERVAL,
                       DEFAULT_CHECKPOINT_INTERVAL_STEPS), 0)


def checkpoint_keep() -> int:
    """Complete checkpoints retained by GC (always >= 1)."""
    return max(get_int(CHECKPOINT_KEEP, DEFAULT_CHECKPOINT_KEEP), 1)


def checkpoint_commit_timeout() -> float:
    """Bound on the coordinator's ack-collection before a manifest
    commit is abandoned."""
    return get_float(CHECKPOINT_COMMIT_TIMEOUT,
                     DEFAULT_CHECKPOINT_COMMIT_TIMEOUT)


def checkpoint_fsync() -> bool:
    return get_bool(CHECKPOINT_FSYNC, True)


def hierarchical_allreduce_setting() -> str:
    """HOROVOD_HIERARCHICAL_ALLREDUCE as off|on|auto. `auto` enables
    the two-level path exactly when the collectively-agreed topology is
    hierarchical (co-located ranks on >1 host) — which is also the only
    time `on` can engage — so the two differ only in intent: `on`
    documents an expectation, `auto` an allowance. Falsey values
    (0/false/no/off/empty-default) are off; anything else is on, which
    keeps the historical get_bool contract."""
    v = get_str(HIERARCHICAL_ALLREDUCE, "").lower()
    if v in ("", "0", "false", "no", "off"):
        return "off"
    return "auto" if v == "auto" else "on"


def hierarchical_mode() -> str:
    """Cross-host schedule knob: slice|leader|auto (see
    HIERARCHICAL_MODE above). Read per call like the ring knobs."""
    v = get_str(HIERARCHICAL_MODE, "auto").lower()
    return v if v in ("slice", "leader", "auto") else "auto"


def hier_arena_setting() -> str:
    """HOROVOD_HIER_ARENA as auto|off (see HIER_ARENA above). Falsey
    values (0/false/no/off) pin the per-pair rings; anything else —
    including typos — is auto, because auto still requires the
    collectively agreed capability bit, so an unknown value can never
    desync the schedule."""
    v = get_str(HIER_ARENA, "auto").lower()
    return "off" if v in ("0", "false", "no", "off") else "auto"


def serving_port() -> int:
    """Front-door port; -1 = serving HTTP disabled (the round loop can
    still be driven programmatically), 0 = ephemeral."""
    return get_int(SERVING_PORT, -1)


def serving_addr() -> str:
    return get_str(SERVING_ADDR, "127.0.0.1")


def serving_max_batch() -> int:
    """Requests per dispatch; always >= 1."""
    return max(get_int(SERVING_MAX_BATCH, DEFAULT_SERVING_MAX_BATCH), 1)


def serving_max_batch_tokens() -> int:
    """Summed token budget per dispatch; always >= 1."""
    return max(get_int(SERVING_MAX_BATCH_TOKENS,
                       DEFAULT_SERVING_MAX_BATCH_TOKENS), 1)


def serving_max_delay_ms() -> float:
    """Max coalescing delay (never a latency floor); floor 0 = dispatch
    every admitted request immediately."""
    return max(get_float(SERVING_MAX_DELAY_MS,
                         DEFAULT_SERVING_MAX_DELAY_MS), 0.0)


def serving_queue_depth() -> int:
    """Admission-queue bound (429 past it); always >= 1."""
    return max(get_int(SERVING_QUEUE_DEPTH, DEFAULT_SERVING_QUEUE_DEPTH), 1)


def serving_request_timeout() -> float:
    """Default per-request deadline in seconds; always > 0."""
    return max(get_float(SERVING_REQUEST_TIMEOUT,
                         DEFAULT_SERVING_REQUEST_TIMEOUT), 0.001)


def serving_weight_refresh_seconds() -> float:
    """Manifest-watch poll cadence; 0 disables weight hot-swap."""
    return max(get_float(SERVING_WEIGHT_REFRESH,
                         DEFAULT_SERVING_WEIGHT_REFRESH), 0.0)


def serving_doors() -> int:
    """How many ranks open redundant HTTP front doors; always >= 1
    (door 0 is the classic single front door). Bogus values fall to
    the default — a typo must never silently disable redundancy the
    operator asked for, so it falls to 1 door, the safe baseline."""
    try:
        return max(get_int(SERVING_DOORS, DEFAULT_SERVING_DOORS), 1)
    except ValueError:
        return DEFAULT_SERVING_DOORS


def serving_stream_enabled() -> bool:
    """Streaming-response master switch; default on. Unary JSON stays
    the default per request either way — this only gates whether a
    {"stream": true} request may stream."""
    return get_bool(SERVING_STREAM, True)


def serving_autoscale_interval_seconds() -> float:
    """Serving-autoscaler cadence in seconds; 0 (default, and the
    fallback for bogus or negative values) disables the policy loop."""
    try:
        return max(get_float(SERVING_AUTOSCALE_INTERVAL, 0.0), 0.0)
    except ValueError:
        return 0.0


def goodput_enabled() -> bool:
    """Goodput ledger master switch; default on (docs/goodput.md)."""
    return get_bool(GOODPUT, True)


def goodput_dir() -> str:
    """Durable ledger-stamp directory; defaults to the checkpoint dir,
    empty = no durable stamps."""
    d = get_str(GOODPUT_DIR, "")
    return d if d else checkpoint_dir()


def goodput_stamp_seconds() -> float:
    """Minimum seconds between ledger-stamp writes; floor 0 (= stamp
    on every commit)."""
    return max(get_float(GOODPUT_STAMP_SECONDS, 0.0), 0.0)


def step_flops() -> float:
    """Declared per-step flop count; 0 (default) = no FLOP/MFU rows.
    Negative or unparsable values fall to 0 — a typo must never turn
    into a bogus efficiency number."""
    try:
        return max(get_float(STEP_FLOPS, 0.0), 0.0)
    except ValueError:
        return 0.0


def goodput_peak_flops() -> float:
    """Peak per-rank FLOP/s for MFU; 0 disables the ratio."""
    try:
        return max(get_float(GOODPUT_PEAK_FLOPS, 0.0), 0.0)
    except ValueError:
        return 0.0


def metrics_sample_seconds() -> float:
    """On-box sampler cadence; <= 0 disables the health plane. Floored
    at 50 ms so a typo cannot turn the sampler into a busy loop."""
    v = get_float(METRICS_SAMPLE_SECONDS, DEFAULT_METRICS_SAMPLE_SECONDS)
    return max(v, 0.05) if v > 0 else 0.0


def metrics_history_samples() -> int:
    """Sampler ring capacity in samples; 0 disables the health plane."""
    return max(get_int(METRICS_HISTORY_SAMPLES,
                       DEFAULT_METRICS_HISTORY_SAMPLES), 0)


def health_plane_enabled() -> bool:
    return metrics_sample_seconds() > 0 and metrics_history_samples() > 0


def alert_rules_spec() -> str:
    """Raw HOROVOD_ALERT_RULES token list (parsed by common/alerts.py)."""
    return get_str(ALERT_RULES, "")


def serving_slo_p99_ms() -> float:
    """Serving p99 latency SLO target in ms; 0 disarms the rule."""
    return max(get_float(SERVING_SLO_P99_MS, 0.0), 0.0)


def metrics_sync_seconds() -> float:
    """Interval between per-rank telemetry pushes to rank 0's fleet view;
    0 disables cross-rank aggregation."""
    return get_float(METRICS_SYNC_SECONDS, DEFAULT_METRICS_SYNC_SECONDS)


def connect_retry_policy() -> "tuple[int, float, float]":
    """(attempts, base backoff seconds, backoff cap seconds)."""
    return (
        max(get_int(CONNECT_ATTEMPTS, DEFAULT_CONNECT_ATTEMPTS), 1),
        get_float(CONNECT_BACKOFF, DEFAULT_CONNECT_BACKOFF_SECONDS),
        get_float(CONNECT_BACKOFF_CAP, DEFAULT_CONNECT_BACKOFF_CAP_SECONDS),
    )
