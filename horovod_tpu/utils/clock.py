"""Shared monotonic/wall clock anchor for every trace producer.

The repo has three timestamp producers that must splice into one
Perfetto view: the host-side Chrome-trace timeline (engine/timeline.py),
the distributed tracing plane's flight recorder (common/tracing.py), and
the XLA profiler's device lanes (engine/mesh_timeline.py). Before this
module each held its own ``time.monotonic_ns()`` origin, so two files
captured in the same process disagreed about where t=0 was and lanes
could not be laid side by side.

One process-wide anchor fixes that: ``MONO_ANCHOR_NS`` /
``WALL_ANCHOR_NS`` are captured once at import, every host trace event's
``ts`` is microseconds since the SAME monotonic anchor (``trace_us``),
and ``anchor_meta()`` stamps the wall-clock identity of that anchor into
each output file so offline tools (and the mesh-timeline splicer) can
align files from different processes — or device lanes with their own
epoch — via wall time.

Cross-RANK alignment is a different problem (different machines,
different clocks) and is solved by the liveness plane's NTP-style
offset estimation (common/health.py clock_offsets); this module only
guarantees that everything inside one process agrees with itself.
"""
from __future__ import annotations

import os
import socket
import time

# Captured once per process; every host-side trace ts derives from it.
MONO_ANCHOR_NS: int = time.monotonic_ns()
WALL_ANCHOR_NS: int = time.time_ns()


def mono_ns() -> int:
    """The one timestamp source for trace events and latency histograms."""
    return time.monotonic_ns()


def monotonic() -> float:
    """Seconds variant for duration math feeding telemetry histograms."""
    return time.monotonic_ns() / 1e9


def anchor_ns() -> int:
    return MONO_ANCHOR_NS


def trace_us(ns: int) -> float:
    """Chrome-trace ``ts``: microseconds since the process anchor."""
    return (ns - MONO_ANCHOR_NS) / 1e3


def mono_to_wall_ns(ns: int) -> int:
    """Map a monotonic stamp to wall-clock ns via the shared anchor."""
    return ns - MONO_ANCHOR_NS + WALL_ANCHOR_NS


def anchor_meta() -> dict:
    """Identity of this process's trace origin, embedded in every trace
    file so offline tools can align files captured by different
    processes (or splice in device lanes timed against wall clock)."""
    return {
        "mono_anchor_ns": MONO_ANCHOR_NS,
        "wall_anchor_ns": WALL_ANCHOR_NS,
        "pid": os.getpid(),
        "host": os.environ.get("HOROVOD_HOSTNAME") or socket.gethostname(),
    }
