"""Retry with exponential backoff + full jitter.

Shared by the rendezvous KV client and the TCP mesh bootstrap (the
reference bounds its store waits the same way: gloo's store_timeout plus
the runner's retry loops, horovod/runner/http/http_client.py:17-45).
Jitter is the standard decorrelation trick: without it, N workers that
all lost the same peer retry in lockstep and hammer the rendezvous
server in synchronized waves.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from . import env as env_cfg
from .logging import get_logger

logger = get_logger()

T = TypeVar("T")


_retry_counter_cache = None


def _retry_counter():
    # Lazy: utils must stay importable without the common package being
    # initialized first (launcher entry points import utils early).
    # Cached after first resolution — call_with_retry runs inside 20Hz
    # bootstrap polling loops and must not pay a registry lookup per call.
    global _retry_counter_cache
    if _retry_counter_cache is None:
        from ..common import telemetry

        _retry_counter_cache = telemetry.counter(
            "horovod_retry_attempts_total",
            "Failed attempts absorbed by retry loops (connects, rendezvous KV)",
        )
    return _retry_counter_cache


def backoff_delays(attempts: int, base: float, cap: float):
    """Yield attempts-1 sleep durations: base doubling per attempt,
    capped, with +/-50% jitter."""
    for i in range(attempts - 1):
        d = min(base * (2 ** i), cap)
        yield d * (0.5 + random.random())


def call_with_retry(
    fn: Callable[[], T],
    what: str,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    no_retry_on: Tuple[Type[BaseException], ...] = (PermissionError,),
    attempts: Optional[int] = None,
    base: Optional[float] = None,
    cap: Optional[float] = None,
    deadline: Optional[float] = None,
) -> T:
    """Call `fn` up to `attempts` times, sleeping a jittered exponential
    backoff between failures. `deadline` (monotonic timestamp) bounds the
    whole loop: no retry starts past it. `no_retry_on` wins over
    `retry_on` (PermissionError by default: an auth rejection — e.g. a
    bad HMAC digest — never heals by retrying). The last failure is
    re-raised with its original type so callers can translate
    precisely."""
    env_attempts, env_base, env_cap = env_cfg.connect_retry_policy()
    attempts = env_attempts if attempts is None else max(attempts, 1)
    base = env_base if base is None else base
    cap = env_cap if cap is None else cap
    delays = list(backoff_delays(attempts, base, cap)) + [0.0]
    last: Optional[BaseException] = None
    counter = _retry_counter()
    for attempt, delay in enumerate(delays, 1):
        try:
            return fn()
        except no_retry_on:
            raise
        except retry_on as exc:
            last = exc
            counter.inc()
            expired = (deadline is not None
                       and time.monotonic() + delay > deadline)
            if attempt >= attempts or expired:
                # Final attempt: one WARNING carries the whole story —
                # what failed, how many attempts it survived, and that
                # the error is about to propagate.
                logger.warning(
                    "%s failed after %d attempt(s): %s; giving up",
                    what, attempt, exc,
                )
                raise
            # Log the FIRST failure at WARNING so a flapping dependency
            # is visible, then count the rest silently in
            # horovod_retry_attempts_total — N workers retrying with
            # backoff otherwise emit O(attempts × ranks) warning lines
            # for one transient blip.
            log = logger.warning if attempt == 1 else logger.debug
            log(
                "%s failed (attempt %d/%d): %s; retrying in %.2fs",
                what, attempt, attempts, exc, delay,
            )
            time.sleep(delay)
    raise last  # pragma: no cover - loop always returns or raises
