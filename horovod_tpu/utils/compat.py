"""Version-compat shims for jax APIs used throughout the framework."""
from __future__ import annotations

import jax

# shard_map moved from jax.experimental to the jax namespace.
try:
    from jax import shard_map as _shard_map  # jax >= 0.4.35ish

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False,
                  axis_names=None):
        kw = {}
        if axis_names is not None:
            # Partial manualization: only these axes become manual;
            # the rest stay under GSPMD inside the body.
            kw["axis_names"] = frozenset(axis_names)
        try:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_vma=check_rep, **kw)
        except TypeError:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              **kw)

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False,
                  axis_names=None):
        kw = {"auto": frozenset(set(mesh.axis_names) - set(axis_names))} \
            if axis_names is not None else {}
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_rep=check_rep, **kw)


import contextlib
import threading

_ambient = threading.local()


def set_mesh(mesh):
    """Ambient-mesh context (jax >= 0.5 jax.sharding.set_mesh). On older
    jax, enter the legacy `with mesh:` context AND track the mesh in a
    thread-local so get_abstract_mesh() below can answer at trace
    time."""
    try:
        return jax.sharding.set_mesh(mesh)
    except AttributeError:
        pass

    @contextlib.contextmanager
    def _cm():
        prev = getattr(_ambient, "mesh", None)
        _ambient.mesh = mesh
        try:
            with mesh:
                yield
        finally:
            _ambient.mesh = prev

    return _cm()


def get_abstract_mesh():
    """jax >= 0.5 jax.sharding.get_abstract_mesh; on older jax, the
    abstract mesh of whatever set_mesh() above made ambient (None when
    nothing is)."""
    try:
        return jax.sharding.get_abstract_mesh()
    except AttributeError:
        m = getattr(_ambient, "mesh", None)
        return None if m is None else m.abstract_mesh


def axis_size(axis_name):
    """Static size of a named mesh axis from inside shard_map/pmap.
    jax >= 0.5 spells it lax.axis_size; on older versions psum of the
    literal 1 constant-folds to the same static Python int."""
    import jax

    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def pvary(x, axis):
    """Mark x as varying over `axis` for shard_map's VMA tracking.
    No-op under check_vma=False (our shard_map default); under VMA
    tracking it keeps jax.grad cotangents rank-local instead of
    auto-psummed, preserving Horovod's per-rank-gradient semantics."""
    import jax

    try:
        return jax.lax.pcast(x, to="varying", axes=axis)
    except (AttributeError, TypeError):
        pass
    try:
        return jax.lax.pvary(x, axis)
    except AttributeError:
        # jax 0.4.x: no VMA tracking at all (shard_map check_rep=False
        # is the only mode we use) — the annotation is a true no-op.
        return x


def tree_map(f, *trees):
    return jax.tree.map(f, *trees)


def tree_leaves(tree):
    return jax.tree.leaves(tree)


def tree_flatten(tree):
    return jax.tree.flatten(tree)


def tree_unflatten(treedef, leaves):
    return jax.tree.unflatten(treedef, leaves)


def tree_leaves_with_path(tree):
    """jax >= 0.5 jax.tree.leaves_with_path; older jax spells it
    jax.tree_util.tree_leaves_with_path."""
    try:
        return jax.tree.leaves_with_path(tree)
    except AttributeError:
        return jax.tree_util.tree_leaves_with_path(tree)
