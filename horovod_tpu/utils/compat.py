"""Version-compat shims for jax APIs used throughout the framework."""
from __future__ import annotations

import jax

# shard_map moved from jax.experimental to the jax namespace.
try:
    from jax import shard_map as _shard_map  # jax >= 0.4.35ish

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False,
                  axis_names=None):
        kw = {}
        if axis_names is not None:
            # Partial manualization: only these axes become manual;
            # the rest stay under GSPMD inside the body.
            kw["axis_names"] = frozenset(axis_names)
        try:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_vma=check_rep, **kw)
        except TypeError:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              **kw)

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False,
                  axis_names=None):
        kw = {"auto": frozenset(set(mesh.axis_names) - set(axis_names))} \
            if axis_names is not None else {}
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_rep=check_rep, **kw)


def pvary(x, axis):
    """Mark x as varying over `axis` for shard_map's VMA tracking.
    No-op under check_vma=False (our shard_map default); under VMA
    tracking it keeps jax.grad cotangents rank-local instead of
    auto-psummed, preserving Horovod's per-rank-gradient semantics."""
    import jax

    try:
        return jax.lax.pcast(x, to="varying", axes=axis)
    except (AttributeError, TypeError):
        return jax.lax.pvary(x, axis)


def tree_map(f, *trees):
    return jax.tree.map(f, *trees)


def tree_leaves(tree):
    return jax.tree.leaves(tree)


def tree_flatten(tree):
    return jax.tree.flatten(tree)


def tree_unflatten(treedef, leaves):
    return jax.tree.unflatten(treedef, leaves)
