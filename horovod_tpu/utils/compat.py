"""Version-compat shims for jax APIs used throughout the framework."""
from __future__ import annotations

import jax

# shard_map moved from jax.experimental to the jax namespace.
try:
    from jax import shard_map as _shard_map  # jax >= 0.4.35ish

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        try:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_vma=check_rep)
        except TypeError:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_rep=check_rep)


def tree_map(f, *trees):
    return jax.tree.map(f, *trees)


def tree_leaves(tree):
    return jax.tree.leaves(tree)


def tree_flatten(tree):
    return jax.tree.flatten(tree)


def tree_unflatten(treedef, leaves):
    return jax.tree.unflatten(treedef, leaves)
