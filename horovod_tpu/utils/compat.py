"""Version-compat shims for jax APIs used throughout the framework."""
from __future__ import annotations

import jax

# shard_map moved from jax.experimental to the jax namespace.
try:
    from jax import shard_map as _shard_map  # jax >= 0.4.35ish

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False,
                  axis_names=None):
        kw = {}
        if axis_names is not None:
            # Partial manualization: only these axes become manual;
            # the rest stay under GSPMD inside the body.
            kw["axis_names"] = frozenset(axis_names)
        try:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_vma=check_rep, **kw)
        except TypeError:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              **kw)

except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _concrete_mesh(mesh):
        """Resolve an AbstractMesh to the ambient CONCRETE mesh.

        jax >= 0.5 accepts an abstract mesh in shard_map (devices come
        from jax.sharding.set_mesh at run time); on this jax an
        abstract mesh silently lowers the surrounding jit as a
        single-device program (mhlo.num_partitions stays 1), and XLA's
        ShardingRemover then replaces the manual-region
        SPMDShardToFullShape custom-calls with their differently-shaped
        operands — an INTERNAL RET_CHECK crash at compile time. The
        concrete mesh is recovered from whichever ambient context is
        live: set_mesh()'s thread-local, else the legacy `with mesh:`
        resource env."""
        import jax.sharding as _jshard

        if not isinstance(mesh, getattr(_jshard, "AbstractMesh", ())):
            return mesh  # already concrete
        cands = [getattr(_ambient, "mesh", None)]
        try:
            from jax._src import mesh as _mesh_lib

            cands.append(_mesh_lib.thread_resources.env.physical_mesh)
        except Exception:  # pragma: no cover - private-API drift
            pass
        for cand in cands:
            if (cand is not None and not cand.empty
                    and cand.axis_names == tuple(mesh.axis_names)
                    and tuple(cand.shape[a] for a in cand.axis_names)
                    == tuple(mesh.shape[a] for a in mesh.axis_names)):
                return cand
        raise ValueError(
            "shard_map over an abstract mesh needs an ambient concrete "
            "mesh on this jax version — enter one via "
            "horovod_tpu.utils.compat.set_mesh(mesh) (or `with mesh:`) "
            f"matching axes {tuple(mesh.axis_names)}"
        )

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False,
                  axis_names=None):
        mesh = _concrete_mesh(mesh)
        # Partial manualization (`auto=` on the experimental API) is
        # broken on this jax/jaxlib line: the SPMD partitioner rejects
        # the PartitionId that `axis_index` lowers to ("PartitionId
        # instruction is not supported for SPMD partitioning"), and even
        # collective-only bodies trip hard CHECK failures in the
        # partitioner's manual-subgroup handling (spmd_partitioner.cc:512,
        # hlo_sharding_util.cc:2750 — process aborts, not exceptions).
        # Fallback: FULL manualization. Axes absent from in_specs/
        # out_specs are treated as replicated, so the body sees exactly
        # the same per-shard shapes as under partial manualization and
        # the results are identical; what is lost is only GSPMD
        # auto-sharding of the body along the unnamed axes (a perf
        # concern on real meshes, not a semantics change — and this
        # branch only runs on jax versions that cannot compile the
        # partial-manual program at all).
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_rep)


import contextlib
import threading

_ambient = threading.local()


def set_mesh(mesh):
    """Ambient-mesh context (jax >= 0.5 jax.sharding.set_mesh). On older
    jax, enter the legacy `with mesh:` context AND track the mesh in a
    thread-local so get_abstract_mesh() below can answer at trace
    time."""
    try:
        return jax.sharding.set_mesh(mesh)
    except AttributeError:
        pass

    @contextlib.contextmanager
    def _cm():
        prev = getattr(_ambient, "mesh", None)
        _ambient.mesh = mesh
        try:
            with mesh:
                yield
        finally:
            _ambient.mesh = prev

    return _cm()


def get_abstract_mesh():
    """jax >= 0.5 jax.sharding.get_abstract_mesh; on older jax, the
    abstract mesh of whatever set_mesh() above made ambient (None when
    nothing is)."""
    try:
        return jax.sharding.get_abstract_mesh()
    except AttributeError:
        m = getattr(_ambient, "mesh", None)
        return None if m is None else m.abstract_mesh


def axis_size(axis_name):
    """Static size of a named mesh axis from inside shard_map/pmap.
    jax >= 0.5 spells it lax.axis_size; on older versions psum of the
    literal 1 constant-folds to the same static Python int."""
    import jax

    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def force_host_device_count(n: int):
    """Force `n` virtual CPU host devices on BOTH jax lines.

    jax >= 0.5 has the `jax_num_cpu_devices` config option; older jax
    only honors the XLA flag, and ONLY if it is set before the first
    backend creation (clear_backends does not re-read XLA_FLAGS for an
    already-materialized client on old jax) — so call this before any
    `jax.devices()`/computation. An existing count in XLA_FLAGS is
    OVERRIDDEN, not kept: a stale =1 from an earlier run silently
    starving a multi-device benchmark is worse than clobbering."""
    import os
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", "")).strip()
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # jax < 0.5: the XLA flag did the job
        pass
    _jeb.clear_backends()


def axis_index(axis_name):
    """`lax.axis_index` through the compat surface.

    On jax 0.4.37 a bare `lax.axis_index` inside a partial-manual
    shard_map lowers to an HLO PartitionId that the SPMD partitioner
    rejects outright; the shard_map wrapper above therefore
    full-manualizes on that version, under which this lowering is
    valid again. Call sites that run inside shard_map bodies should
    use this instead of `lax.axis_index` directly so the two shims
    stay paired."""
    import jax

    return jax.lax.axis_index(axis_name)


def pvary(x, axis):
    """Mark x as varying over `axis` for shard_map's VMA tracking.
    No-op under check_vma=False (our shard_map default); under VMA
    tracking it keeps jax.grad cotangents rank-local instead of
    auto-psummed, preserving Horovod's per-rank-gradient semantics."""
    import jax

    try:
        return jax.lax.pcast(x, to="varying", axes=axis)
    except (AttributeError, TypeError):
        pass
    try:
        return jax.lax.pvary(x, axis)
    except AttributeError:
        # jax 0.4.x: no VMA tracking at all (shard_map check_rep=False
        # is the only mode we use) — the annotation is a true no-op.
        return x


def tree_map(f, *trees):
    return jax.tree.map(f, *trees)


def tree_leaves(tree):
    return jax.tree.leaves(tree)


def tree_flatten(tree):
    return jax.tree.flatten(tree)


def tree_unflatten(treedef, leaves):
    return jax.tree.unflatten(treedef, leaves)


def tree_leaves_with_path(tree):
    """jax >= 0.5 jax.tree.leaves_with_path; older jax spells it
    jax.tree_util.tree_leaves_with_path."""
    try:
        return jax.tree.leaves_with_path(tree)
    except AttributeError:
        return jax.tree_util.tree_leaves_with_path(tree)
