"""Crash-safe atomic file writes: one tmp+rename protocol for every
local-filesystem writer.

The write-then-`os.replace` dance — readers never observe a partial
file, a crash leaves only a recognizable ``*.tmp.*`` orphan — used to be
hand-rolled in four places (`spark/store.py`, the metrics JSON dump, the
flight-recorder/post-mortem dumps, the merged trace file) and is now
also the foundation of the checkpoint shard writer
(`common/checkpoint.py`, docs/checkpoint.md). One module, one tmp-name
scheme (``<path>.tmp.<pid>.<mono_ns>``), one cleanup contract: on any
failure the tmp file is unlinked and the destination is untouched.

Durability note: `os.replace` gives *atomicity* (all-or-nothing name
binding); `fsync=True` additionally forces the data to stable storage
before the rename AND the parent directory entry after it — without
the latter the bytes survive power loss but the name binding may not,
which is what a checkpoint needs to survive power loss rather than
mere process death. Metadata writers skip the fsync — a lost metrics
snapshot costs nothing.

Fault injection: every write consults the chaos injector's disk hooks
(``diskfail:`` / ``diskslow:`` rules, docs/fault_tolerance.md) so disk
full / slow-NFS scenarios are deterministic, unit-testable inputs. An
injected failure surfaces as `OSError` — exactly what a real disk
error raises — so callers exercise their real error paths.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional, Union

TMP_MARKER = ".tmp."


def tmp_path_for(path: str) -> str:
    """The tmp name a write of `path` uses. Unique per process *and*
    call (monotonic-ns suffix), so concurrent writers of one path never
    collide and a crashed writer's orphan never blocks a retry."""
    return f"{path}{TMP_MARKER}{os.getpid()}.{time.monotonic_ns()}"


def is_tmp_debris(name: str) -> bool:
    """Whether a file name is an orphaned tmp from an interrupted write
    (checkpoint discovery and GC must ignore — and may delete — these)."""
    return TMP_MARKER in name


def _fsync_dir(dirpath: str):
    """Force the directory entry — the rename itself — to stable
    storage; without this the *data* survives power loss but the name
    binding may not, and a 'committed' checkpoint vanishes. Best
    effort: some filesystems refuse fsync on a directory fd (EINVAL),
    where the filesystem's own ordering guarantee is the best
    available."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _check_disk(op: str, path: str):
    # Lazy import: utils must stay importable without the common package
    # fully initialized (logging bootstraps through here). get_injector
    # (not the raw singleton) so HOROVOD_FAULT_INJECT disk rules fire
    # even in processes where no transport ever loaded the env spec.
    from ..common.fault_injection import get_injector

    inj = get_injector()
    if inj.active:
        inj.check_disk(op, path)


def atomic_write(path: str, fill: Callable, mode: str = "wb",
                 make_dirs: bool = True, fsync: bool = False) -> str:
    """Write `path` atomically: `fill(f)` populates a tmp file which is
    then renamed over `path`. Returns `path`. On any failure the tmp is
    removed and the previous `path` (if any) is left intact."""
    _check_disk("write", path)
    if make_dirs:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
    tmp = tmp_path_for(path)
    try:
        with open(tmp, mode) as f:
            fill(f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: readers never see partial files
        if fsync:
            _fsync_dir(os.path.dirname(path))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def atomic_write_bytes(path: str, data: bytes, make_dirs: bool = True,
                       fsync: bool = False) -> str:
    return atomic_write(path, lambda f: f.write(data), mode="wb",
                        make_dirs=make_dirs, fsync=fsync)


def atomic_write_text(path: str, text: str, make_dirs: bool = True,
                      fsync: bool = False) -> str:
    return atomic_write(path, lambda f: f.write(text), mode="w",
                        make_dirs=make_dirs, fsync=fsync)


def checked_read_bytes(path: str) -> bytes:
    """Read a whole file through the disk fault hooks (``diskfail`` with
    ``op=read`` exercises restore-time error handling)."""
    _check_disk("read", path)
    with open(path, "rb") as f:
        return f.read()
