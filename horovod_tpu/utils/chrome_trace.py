"""Chrome-trace file plumbing shared by every trace consumer.

The glob/gzip/parse dance over a ``jax.profiler`` output directory used
to be duplicated between ``engine/mesh_timeline.py`` (device-lane
splicing) and ``scripts/profile_step.py`` (per-op step breakdown); the
tracing plane's analyzers (scripts/critical_path.py) need the same
readers for merged traces and post-mortems. One module, three
consumers.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
from typing import List, Optional


def read_trace_file(path: str):
    """Load one Chrome-trace JSON file (.json or .json.gz). Returns the
    parsed document: either a top-level event list or an object with a
    ``traceEvents`` key — see ``trace_events`` for the normalizer."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def trace_events(doc) -> List[dict]:
    """Normalize a Chrome-trace document to its event list. The format
    allows both a bare top-level array and {"traceEvents": [...]} — and
    ``data.get`` on a list raises before any default applies, which is
    exactly the bug this helper exists to fix once."""
    if isinstance(doc, list):
        return doc
    return doc.get("traceEvents", [])


def load_profiler_events(profile_dir: str) -> Optional[List[dict]]:
    """Events of the newest trace.json(.gz) under a profiler output dir
    (``jax.profiler`` nests them under plugins/profile/<ts>/). None when
    the profiler produced nothing."""
    paths = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(profile_dir, "**", "*.trace.json"),
                    recursive=True)
    )
    if not paths:
        return None
    return trace_events(read_trace_file(paths[-1]))


def instant(name: str, ts_us: float, pid: int = 0, tid: int = 0,
            scope: str = "p", cat: Optional[str] = None,
            args: Optional[dict] = None) -> dict:
    """One Chrome-trace instant event ("ph": "i") — the vertical marker
    lane-annotation form the lifecycle events plane (docs/events.md)
    uses to land re-mesh/drain/swap markers inline with spans. `scope`:
    "g" draws the line across the whole trace, "p" across the process
    lane, "t" on one thread."""
    ev = {"ph": "i", "name": name, "pid": pid, "tid": tid,
          "ts": ts_us, "s": scope}
    if cat:
        ev["cat"] = cat
    if args:
        ev["args"] = args
    return ev


def instant_events(doc) -> List[dict]:
    """Every instant event in a trace document (tests + analyzers)."""
    return [e for e in trace_events(doc) if e.get("ph") == "i"]


def write_trace(path: str, events: List[dict], metadata: Optional[dict] = None):
    """Write events as a ``{"traceEvents": [...]}`` document (the object
    form — Perfetto accepts extra top-level keys, so tool metadata rides
    along without confusing the viewer). Atomic (tmp+rename) so a crash
    mid-dump never leaves a torn JSON where a viewer expects a trace."""
    doc = {"traceEvents": events}
    if metadata:
        doc.update(metadata)
    from . import atomic_file

    atomic_file.atomic_write(path, lambda f: json.dump(doc, f), mode="w")
