"""Host parsing and rank/slot assignment.

(ref: horovod/runner/common/util/hosts.py:106-155 — parse_hosts +
get_host_assignments packing hosts in order into SlotInfo{rank,
local_rank, cross_rank, sizes}.)

On TPU pods the "hosts" are TPU-VM workers; `discover_tpu_hosts` maps
the slice topology into the same HostInfo shape so one assignment path
serves ssh clusters and TPU slices.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(host_string: str) -> "HostInfo":
        if ":" in host_string:
            hostname, slots = host_string.strip().rsplit(":", 1)
            return HostInfo(hostname, int(slots))
        return HostInfo(host_string.strip(), 1)


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_response_string(self) -> str:
        # Wire format used by the elastic rendezvous `rank_and_size`
        # endpoint (ref: runner/elastic/rendezvous.py:40-52).
        return ",".join(
            str(v) for v in (
                self.rank, self.size, self.local_rank, self.local_size,
                self.cross_rank, self.cross_size,
            )
        )


INVALID_SLOT = SlotInfo("", -1, -1, -1, -1, -1, -1)


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """"h1:2,h2:4" → [HostInfo] (ref: hosts.py parse_hosts)."""
    return [HostInfo.from_string(s) for s in hosts_string.split(",") if s]


def parse_hostfile(path: str) -> List[HostInfo]:
    """mpirun-style hostfile: `host slots=N` or `host:N` per line."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, slots = line.partition("slots=")
                out.append(HostInfo(name.strip(), int(slots)))
            else:
                out.append(HostInfo.from_string(line))
    return out


def get_host_assignments(
    hosts: List[HostInfo], min_np: int, max_np: Optional[int] = None
) -> List[SlotInfo]:
    """Pack hosts in order into global/local/cross ranks
    (ref: hosts.py:106-155). Raises if fewer than min_np slots exist;
    stops at max_np slots when given."""
    rank = 0
    assignments: List[List[SlotInfo]] = []
    for cross_rank_base, host in enumerate(hosts):
        local: List[SlotInfo] = []
        for local_rank in range(host.slots):
            if max_np is not None and rank >= max_np:
                break
            local.append(
                SlotInfo(
                    hostname=host.hostname,
                    rank=rank,
                    local_rank=local_rank,
                    cross_rank=len(assignments),
                    size=0,
                    local_size=0,
                    cross_size=0,
                )
            )
            rank += 1
        if local:
            assignments.append(local)
    world = rank
    if world < min_np:
        raise ValueError(
            f"requested {min_np} processes but hosts provide only {world} "
            f"slots: {[f'{h.hostname}:{h.slots}' for h in hosts]}"
        )
    # Fill sizes: local_size per host, cross_size per local_rank column.
    slots = [s for host_slots in assignments for s in host_slots]
    local_sizes = {i: len(hs) for i, hs in enumerate(assignments)}
    cross_sizes: Dict[int, int] = {}
    for s in slots:
        cross_sizes[s.local_rank] = cross_sizes.get(s.local_rank, 0) + 1
    for s in slots:
        s.size = world
        s.local_size = local_sizes[s.cross_rank]
        s.cross_size = cross_sizes[s.local_rank]
    return slots


def discover_tpu_hosts() -> Optional[List[HostInfo]]:
    """TPU-VM slice topology → hosts (one slot per host process; chips
    are addressed through the jax mesh, not extra ranks). Returns None
    off-TPU. (Replaces the reference's ssh+NIC probing,
    ref: runner/driver/driver_service.py:124-192, per SURVEY.md §5.8.)

    Detection order: Cloud TPU VM metadata env (TPU_WORKER_HOSTNAMES,
    set on every worker of a pod slice), then an initialized
    jax.distributed process group."""
    import os

    names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if names:
        hosts = [h.strip() for h in names.split(",") if h.strip()]
        if len(hosts) > 1:
            return [HostInfo(h, 1) for h in hosts]
    try:
        import jax

        n = jax.process_count()
        if n <= 1:
            return None
        return [HostInfo(f"process-{i}", 1) for i in range(n)]
    except Exception:  # pragma: no cover
        return None
