"""CLI args / config file → HOROVOD_* environment mapping.

(ref: horovod/runner/common/util/config_parser.py — ~30 knobs funneled
from `horovodrun` flags into env; the same names here so reference launch
scripts port unchanged.)
"""
from __future__ import annotations

from typing import Dict, Optional

from ..utils import env as env_cfg

# argparse dest → env name
_ARG_TO_ENV = {
    "fusion_threshold_mb": env_cfg.FUSION_THRESHOLD,
    "cycle_time_ms": env_cfg.CYCLE_TIME,
    "cache_capacity": env_cfg.CACHE_CAPACITY,
    "hierarchical_allreduce": env_cfg.HIERARCHICAL_ALLREDUCE,
    "hierarchical_allgather": env_cfg.HIERARCHICAL_ALLGATHER,
    "autotune": env_cfg.AUTOTUNE,
    "autotune_log_file": env_cfg.AUTOTUNE_LOG,
    "timeline_filename": env_cfg.TIMELINE,
    "timeline_mark_cycles": env_cfg.TIMELINE_MARK_CYCLES,
    "no_stall_check": env_cfg.STALL_CHECK_DISABLE,
    "stall_check_warning_time_seconds": env_cfg.STALL_CHECK_TIME,
    "stall_check_shutdown_time_seconds": env_cfg.STALL_SHUTDOWN_TIME,
    "log_level": env_cfg.LOG_LEVEL,
}


def args_to_env(args) -> Dict[str, str]:
    """Build the env additions for worker processes from parsed args."""
    env: Dict[str, str] = {}
    for dest, name in _ARG_TO_ENV.items():
        val = getattr(args, dest, None)
        if val is None or val is False:
            continue
        if dest == "fusion_threshold_mb":
            env[name] = str(int(float(val) * 1024 * 1024))
        elif val is True:
            env[name] = "1"
        else:
            env[name] = str(val)
    return env


def add_engine_args(parser):
    """Engine knob flags (ref: launch.py parser groups)."""
    g = parser.add_argument_group("tuning")
    g.add_argument("--fusion-threshold-mb", type=float, default=None,
                   help="tensor fusion threshold in MB (default 64)")
    g.add_argument("--cycle-time-ms", type=float, default=None,
                   help="background cycle time in ms (default 5)")
    g.add_argument("--cache-capacity", type=int, default=None,
                   help="response cache capacity (default 1024; 0 disables)")
    g.add_argument("--hierarchical-allreduce", action="store_true",
                   default=None)
    g.add_argument("--hierarchical-allgather", action="store_true",
                   default=None)
    g.add_argument("--autotune", action="store_true", default=None)
    g.add_argument("--autotune-log-file", default=None)
    t = parser.add_argument_group("observability")
    t.add_argument("--timeline-filename", default=None,
                   help="write a Chrome-tracing timeline here (rank 0)")
    t.add_argument("--timeline-mark-cycles", action="store_true",
                   default=None)
    t.add_argument("--no-stall-check", action="store_true", default=None)
    t.add_argument("--stall-check-warning-time-seconds", type=float,
                   default=None)
    t.add_argument("--stall-check-shutdown-time-seconds", type=float,
                   default=None)
    t.add_argument("--log-level", default=None,
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"])
    return parser
