"""Launcher package: hvdrun CLI + programmatic run() API
(ref: horovod/runner/__init__.py:90 horovod.run)."""
from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

from .hosts import HostInfo, SlotInfo, get_host_assignments, parse_hosts
from .launch import launch_static, make_parser, run_commandline
from .rendezvous_server import RendezvousServer


def run(
    func: Callable[[], Any],
    args=(),
    kwargs=None,
    np: int = 1,
    hosts: Optional[str] = None,
    extra_env: Optional[Dict[str, str]] = None,
    verbose: bool = False,
) -> List[Any]:
    """Run `func` on np processes; returns per-rank results in rank order
    (ref: horovod/runner/__init__.py:90 `horovod.run`). The function is
    pickled (cloudpickle when available) and shipped to workers; results
    come back through the rendezvous KV."""
    try:
        import cloudpickle as pickler
    except ImportError:  # pragma: no cover
        pickler = pickle

    import functools

    payload = pickler.dumps(
        functools.partial(func, *args, **(kwargs or {}))
    )
    host_list = parse_hosts(hosts) if hosts else [HostInfo("localhost", np)]
    slots = get_host_assignments(host_list, np, np)

    from .util import secret as secret_util

    server = RendezvousServer(secret_key=secret_util.make_secret_key())
    server.start()
    try:
        with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as f:
            f.write(payload)
            func_path = f.name
        command = [sys.executable, "-m", "horovod_tpu.runner.task_runner",
                   func_path]
        env = dict(extra_env or {})
        # Prepend the repo root but keep the parent's PYTHONPATH — user
        # functions may need it to unpickle/import on workers (spawn_worker
        # overlays this env on os.environ, so dropping it here loses it).
        inherited = os.environ.get("PYTHONPATH")
        parts = (
            [os.path.dirname(os.path.dirname(os.path.dirname(__file__)))]
            + sys.path[1:2]
            + ([inherited] if inherited else [])
        )
        env.setdefault("PYTHONPATH", os.pathsep.join(parts))
        # Workers default to CPU: a parent holding a single tunneled TPU
        # (JAX_PLATFORMS=axon et al.) would otherwise leak a platform
        # the workers cannot re-register and crash at first jax use.
        # Callers opt workers back onto accelerators by setting
        # JAX_PLATFORMS in extra_env — in that case the platform's
        # bootstrap env (e.g. PALLAS_AXON_POOL_IPS) is left inherited.
        if "JAX_PLATFORMS" not in env:
            env["JAX_PLATFORMS"] = "cpu"
            env.setdefault("PALLAS_AXON_POOL_IPS", "")
        rc = launch_static(slots, command, env, verbose, rendezvous=server,
                           prefix_output=not verbose)
        if rc != 0:
            raise RuntimeError(f"hvdrun function job failed with exit code {rc}")
        results = []
        for r in range(np):
            blob = server.handle_get(f"results/{r}")
            if blob is None:
                raise RuntimeError(f"rank {r} produced no result")
            results.append(pickle.loads(blob))
        return results
    finally:
        server.stop()
        try:
            os.unlink(func_path)
        except OSError:
            pass
