"""`hvdrun` — the horovodrun-equivalent launcher.

(ref: horovod/runner/launch.py:715 CLI, gloo_run.py:65-258 worker
spawn/env contract.) Static launch path:

    hvdrun -np 2 python train.py
    hvdrun -np 4 -H h1:2,h2:2 python train.py

Per slot, the launcher exports the HOROVOD_RANK/SIZE/LOCAL_*/CROSS_* env
(exactly the reference's gloo env contract so `hvd.init()` picks process
mode), plus the rendezvous address of the driver's HTTP KV server the
TCP backend full-meshes through. Remote hosts launch over ssh; TPU-VM
slices are discovered from jax process topology instead of NIC probing
(SURVEY.md §5.8). Elastic mode (`--min-np/--max-np/--host-discovery-
script`) is driven by runner.elastic.driver.
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence

from ..utils import env as env_cfg
from . import config_parser
from .hosts import HostInfo, SlotInfo, get_host_assignments, parse_hostfile, parse_hosts
from .rendezvous_server import RendezvousServer

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def is_local_host(hostname: str) -> bool:
    # HVDRUN_FORCE_LOCAL: treat every host as local — lets elastic tests
    # use distinct fake hostnames on one machine without ssh (the
    # reference's elastic integration tests do the same through ssh to
    # localhost aliases, test/integration/elastic_common.py).
    if os.environ.get("HVDRUN_FORCE_LOCAL"):
        return True
    if hostname in _LOCAL_NAMES or hostname.startswith("process-"):
        return True
    try:
        return hostname in (socket.gethostname(), socket.getfqdn())
    except OSError:  # pragma: no cover
        return False


def slot_env(
    slot: SlotInfo,
    rendezvous_addr: str,
    rendezvous_port: int,
    extra_env: Optional[Dict[str, str]] = None,
    elastic: bool = False,
    secret_key: Optional[bytes] = None,
) -> Dict[str, str]:
    """The worker env contract (ref: gloo_run.py:65-198 _slot_info_to_command)."""
    env = {
        env_cfg.RANK: str(slot.rank),
        env_cfg.SIZE: str(slot.size),
        env_cfg.LOCAL_RANK: str(slot.local_rank),
        env_cfg.LOCAL_SIZE: str(slot.local_size),
        env_cfg.CROSS_RANK: str(slot.cross_rank),
        env_cfg.CROSS_SIZE: str(slot.cross_size),
        env_cfg.RENDEZVOUS_ADDR: rendezvous_addr,
        env_cfg.RENDEZVOUS_PORT: str(rendezvous_port),
        env_cfg.HOSTNAME: slot.hostname,
        env_cfg.CONTROLLER: "tcp",
        env_cfg.CPU_OPERATIONS: "tcp",
    }
    if elastic:
        env[env_cfg.ELASTIC] = "1"
    if secret_key is not None:
        from .util import secret as secret_util

        env[env_cfg.SECRET_KEY] = secret_util.key_to_env(secret_key)
    if extra_env:
        env.update(extra_env)
    return env


def build_ssh_command(
    hostname: str, command: Sequence[str], env: Dict[str, str],
    ssh_port: Optional[int] = None, ssh_identity_file: Optional[str] = None,
) -> List[str]:
    """ssh invocation for a remote slot (ref: runner/util/remote.py).

    The per-job HMAC secret must never appear on a command line — it
    would be world-readable via /proc/*/cmdline on both ends for the
    whole run. When `env` carries it, the remote command instead reads
    one line from stdin into HOROVOD_SECRET_KEY; the caller writes the
    key to the ssh client's stdin (see spawn_worker)."""
    env = dict(env)
    has_secret = env_cfg.SECRET_KEY in env
    env.pop(env_cfg.SECRET_KEY, None)
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
    )
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    if ssh_identity_file:
        ssh += ["-i", ssh_identity_file]
    remote_cmd = f"cd {shlex.quote(os.getcwd())} && env {exports} " + " ".join(
        shlex.quote(c) for c in command
    )
    if has_secret:
        remote_cmd = (
            f"IFS= read -r {env_cfg.SECRET_KEY} && "
            f"export {env_cfg.SECRET_KEY} && " + remote_cmd
        )
    return ssh + [hostname, remote_cmd]


class WorkerHandle:
    """One launched worker. Subclasses change the transport (direct
    subprocess / ssh vs authenticated task service)."""

    def __init__(self, slot: SlotInfo, proc: subprocess.Popen):
        self.slot = slot
        self.proc = proc
        self.threads: List[threading.Thread] = []

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        return self.proc.wait(timeout=timeout)

    def terminate(self):
        # Teardown reuses the drain protocol: the configured preemption
        # signal lets workers treat launcher shutdown exactly like a
        # platform preemption notice (checkpoint-now, clean exit).
        try:
            os.killpg(os.getpgid(self.proc.pid), env_cfg.preempt_signal())
        except (ProcessLookupError, PermissionError):
            pass

    def kill(self):
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


class TaskServiceWorkerHandle(WorkerHandle):
    """Worker driven through a remote host's authenticated TaskService
    (ref: the reference launches remote commands through
    BasicTaskService RPC instead of a long-lived ssh per worker,
    common/service/task_service.py). `proc` is the ssh/local bootstrap
    that hosts the service; the worker command itself runs as the
    service's subprocess."""

    # RPC polls open a TCP connection each time; 4 Hz is plenty for
    # exit detection and keeps per-worker overhead trivial.
    POLL_INTERVAL = 0.25

    def __init__(self, slot: SlotInfo, proc: subprocess.Popen, client,
                 prefix_output: bool = True):
        super().__init__(slot, proc)
        self.client = client
        self._prefix = prefix_output
        self._out_off = 0
        self._rc: Optional[int] = None
        self._last_poll = 0.0

    def _emit(self, delta: bytes):
        if not self._prefix or not delta:
            return
        for line in delta.decode(errors="replace").splitlines():
            sys.stdout.write(f"[{self.slot.rank}]<stdout>:{line}\n")
        sys.stdout.flush()

    def poll(self) -> Optional[int]:
        import time as _time

        if self._rc is not None:
            return self._rc
        now = _time.monotonic()
        if now - self._last_poll < self.POLL_INTERVAL:
            return None
        self._last_poll = now
        try:
            # Offset-based: only new output crosses the wire.
            r = self.client.command_exit_code(self._out_off)
        except (ConnectionError, OSError, EOFError):
            # Service unreachable: fall back to the bootstrap process.
            rc = self.proc.poll()
            if rc is not None:
                self._rc = rc if rc != 0 else 1
            return self._rc
        self._emit(r.output)
        self._out_off += len(r.output)
        if r.terminated:
            self._rc = r.exit_code
        return self._rc

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and _time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("task-service-worker",
                                                timeout)
            _time.sleep(0.1)
        return self._rc

    def terminate(self):
        try:
            self.client.terminate()          # SIGTERM the worker command
        except (ConnectionError, OSError, EOFError, RuntimeError):
            pass
        try:
            self.client.shutdown_service()   # stop the remote service
        except (ConnectionError, OSError, EOFError, RuntimeError):
            pass
        super().terminate()  # the bootstrap ssh/local client process

    def kill(self):
        self.terminate()
        super().kill()


def _forward_stream(stream, sink, prefix: str):
    for line in iter(stream.readline, b""):
        try:
            sink.write(f"{prefix}{line.decode(errors='replace')}")
            sink.flush()
        except ValueError:  # sink closed
            break
    stream.close()


def spawn_worker(
    slot: SlotInfo,
    command: Sequence[str],
    env: Dict[str, str],
    verbose: bool = False,
    prefix_output: bool = True,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
) -> WorkerHandle:
    full_env = dict(os.environ)
    full_env.update(env)
    remote = not is_local_host(slot.hostname)
    secret = env.get(env_cfg.SECRET_KEY) if remote else None
    if remote:
        argv = build_ssh_command(slot.hostname, command, env, ssh_port,
                                 ssh_identity_file)
    else:
        argv = list(command)
    proc = subprocess.Popen(
        argv,
        env=full_env,
        stdin=subprocess.PIPE if secret else None,
        stdout=subprocess.PIPE if prefix_output else None,
        stderr=subprocess.PIPE if prefix_output else None,
        start_new_session=True,  # own process group for clean teardown
    )
    if secret:
        # The remote command's leading `read` consumes this line; the
        # key rides the encrypted channel, not the command line.
        try:
            proc.stdin.write((secret + "\n").encode())
            proc.stdin.flush()
        except (BrokenPipeError, OSError):
            pass
    handle = WorkerHandle(slot, proc)
    if prefix_output:
        # Rank-prefixed output forwarding, reference format "[1]<stdout>:"
        # (ref: gloo_run.py:149-162, safe_shell_exec.py:81-120).
        for stream, sink, tag in (
            (proc.stdout, sys.stdout, "stdout"),
            (proc.stderr, sys.stderr, "stderr"),
        ):
            t = threading.Thread(
                target=_forward_stream,
                args=(stream, sink, f"[{slot.rank}]<{tag}>:"),
                daemon=True,
            )
            t.start()
            handle.threads.append(t)
    return handle


def terminate_workers(handles: List[WorkerHandle]):
    for h in handles:
        if h.poll() is None:
            h.terminate()
    # Workers received a preemption notice (see WorkerHandle.terminate)
    # and may be writing their drain checkpoint: wait out the drain
    # grace budget, not an arbitrary 10s, before escalating to SIGKILL.
    grace = max(10.0, env_cfg.drain_grace_seconds())
    for h in handles:
        try:
            h.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            h.kill()


def launch_static(
    slots: List[SlotInfo],
    command: Sequence[str],
    extra_env: Optional[Dict[str, str]] = None,
    verbose: bool = False,
    rendezvous: Optional[RendezvousServer] = None,
    prefix_output: bool = True,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
) -> int:
    """Run one process per slot; first failure tears everything down
    (ref: gloo_run.py:243-258). Returns the first nonzero exit code or 0."""
    own_server = rendezvous is None
    if own_server:
        # Per-job shared secret: workers must present it to the KV
        # store (ref: secret.py make_secret_key; shipped via env like
        # the reference's _HOROVOD_SECRET_KEY plumbing).
        from .util import secret as secret_util

        server = RendezvousServer(secret_key=secret_util.make_secret_key())
    else:
        server = rendezvous
    port = server.start() if own_server else server.port
    addr = (
        "127.0.0.1"
        if all(is_local_host(s.hostname) for s in slots)
        else _driver_addr()
    )
    # HVDRUN_USE_TASK_SERVICE routes worker exec through per-slot
    # authenticated TaskServices instead of long-lived ssh sessions
    # ("1": remote slots only; "all": every slot — the no-ssh test
    # spelling). Requires the per-job secret, so only with own_server
    # or a keyed external server.
    ts_mode = os.environ.get("HVDRUN_USE_TASK_SERVICE", "")
    driver_service = None
    if ts_mode and server.secret_key is not None:
        ts_slots = [
            i for i, s in enumerate(slots)
            if ts_mode == "all" or not is_local_host(s.hostname)
        ]
    else:
        ts_slots = []
    # Everything from here shares one cleanup block: a failure while
    # spawning (ssh missing, task-service registration timeout, ...)
    # must tear down already-started workers, task-service bootstraps,
    # the DriverService, and the rendezvous server — not leak them.
    handles: List[WorkerHandle] = []
    exit_code = 0
    try:
        if ts_slots:
            driver_service, ts_handles = _spawn_via_task_service(
                [slots[i] for i in ts_slots], command,
                [slot_env(slots[i], addr, port, extra_env,
                          secret_key=server.secret_key) for i in ts_slots],
                server.secret_key, prefix_output, ssh_port,
                ssh_identity_file,
            )
        else:
            ts_handles = []
        ts_iter = iter(ts_handles)
        for i, slot in enumerate(slots):
            if i in ts_slots:
                handles.append(next(ts_iter))
            else:
                handles.append(spawn_worker(
                    slot, command,
                    slot_env(slot, addr, port, extra_env,
                             secret_key=server.secret_key),
                    verbose, prefix_output, ssh_port, ssh_identity_file,
                ))
        pending = set(range(len(handles)))
        while pending:
            for i in sorted(pending):
                rc = handles[i].poll()
                if rc is None:
                    continue
                pending.discard(i)
                if rc != 0:
                    exit_code = exit_code or rc
                    if verbose:
                        print(
                            f"hvdrun: rank {handles[i].slot.rank} exited "
                            f"with {rc}; terminating remaining workers",
                            file=sys.stderr,
                        )
                    terminate_workers([handles[j] for j in pending])
                    for j in list(pending):
                        pending.discard(j)
                    break
            else:
                import time

                time.sleep(0.05)
    except BaseException:
        # Spawn-time failure: stop whatever already started.
        terminate_workers(handles)
        raise
    finally:
        for h in handles:
            for t in h.threads:
                t.join(timeout=5)
        for h in handles:
            # Task-service bootstraps outlive their worker command;
            # shut them down explicitly.
            if isinstance(h, TaskServiceWorkerHandle):
                h.terminate()
        if driver_service is not None:
            driver_service.shutdown()
        if own_server:
            server.stop()
    return exit_code


def _spawn_via_task_service(
    slots: List[SlotInfo],
    command: Sequence[str],
    envs: List[Dict[str, str]],
    secret_key: bytes,
    prefix_output: bool,
    ssh_port: Optional[int],
    ssh_identity_file: Optional[str],
):
    """Bootstrap one TaskService per slot (ssh for remote hosts, plain
    subprocess for local ones), wait for their authenticated
    registrations at the DriverService, then start each worker command
    through TaskClient.run_command (ref: the reference's driver/task
    service launch flow, common/service/driver_service.py +
    task_service.py; ssh only bootstraps, exec rides the HMAC RPC)."""
    from .service import DriverClient, DriverService, TaskClient
    from .util import secret as secret_util

    driver_service = DriverService(num_tasks=len(slots), key=secret_key)
    driver_addr = (
        "127.0.0.1" if all(is_local_host(s.hostname) for s in slots)
        else _driver_addr()
    )
    boot_env = {env_cfg.SECRET_KEY: secret_util.key_to_env(secret_key)}
    boots = []
    for i, slot in enumerate(slots):
        boot_cmd = [
            sys.executable, "-m", "horovod_tpu.runner.task_runner",
            "--task-service", "--index", str(i),
            "--driver", f"{driver_addr}:{driver_service.port}",
        ]
        remote = not is_local_host(slot.hostname)
        if remote:
            # build_ssh_command strips the secret from the command line;
            # it is written to the ssh client's stdin below.
            argv = build_ssh_command(slot.hostname, boot_cmd, boot_env,
                                     ssh_port, ssh_identity_file)
            full_env = dict(os.environ)
        else:
            argv = boot_cmd
            full_env = dict(os.environ)
            full_env.update(boot_env)
        p = subprocess.Popen(
            argv, env=full_env, start_new_session=True,
            stdin=subprocess.PIPE if remote else None,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        if remote:
            try:
                p.stdin.write(
                    (boot_env[env_cfg.SECRET_KEY] + "\n").encode())
                p.stdin.flush()
            except (BrokenPipeError, OSError):
                pass
        boots.append(p)
    handles: List[WorkerHandle] = []
    try:
        addrs = driver_service.wait_for_all_tasks(timeout=120)
        for i, slot in enumerate(slots):
            host = ("127.0.0.1" if is_local_host(slot.hostname)
                    else slot.hostname)
            ts_port = next(iter(addrs[i].values()))
            client = TaskClient(host, ts_port, secret_key)
            client.run_command(list(command), env=envs[i])
            handles.append(TaskServiceWorkerHandle(
                slot, boots[i], client, prefix_output=prefix_output,
            ))
    except BaseException:
        # Registration timeout or a run_command failure: none of the
        # bootstraps may leak (they never exit on their own).
        for h in handles:
            h.terminate()
        for p in boots:
            p.kill()
        driver_service.shutdown()
        raise
    return driver_service, handles


def _driver_addr() -> str:
    # Workers must reach the driver's rendezvous server. For local-only
    # launches 127.0.0.1 works; for remote hosts use the routable name.
    return os.environ.get("HVDRUN_DRIVER_ADDR") or socket.gethostname()


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job "
        "(horovodrun equivalent)",
    )
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="total number of processes")
    p.add_argument("-H", "--hosts", default=None,
                   help='comma list "host1:slots,host2:slots"')
    p.add_argument("--hostfile", default=None,
                   help="mpirun-style hostfile")
    p.add_argument("--network-interface", default=None,
                   help="NIC to bind (informational; TCP mesh binds all)")
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("--ssh-identity-file", default=None)
    p.add_argument("--verbose", "-v", action="store_true")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print which frameworks and backends this "
                   "build supports, then exit "
                   "(ref: horovodrun --check-build)")
    p.add_argument("--disable-output-prefix", action="store_true",
                   help="don't prefix worker output with [rank]<>")
    # Elastic (ref: launch.py elastic flags)
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None)
    p.add_argument("--slots-per-host", type=int, default=None)
    p.add_argument("--reset-limit", type=int, default=None)
    p.add_argument("--config-file", default=None,
                   help="YAML file of flag defaults "
                   "(ref: horovodrun --config-file, launch.py:212+)")
    config_parser.add_engine_args(p)
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command, e.g. python train.py")
    return p


def _apply_config_file(parser: argparse.ArgumentParser, args):
    """Fill unset args from a YAML config file: flat `dest: value`
    mapping, with nested sections flattened (`a: {b-c: 1}` → dest
    `b_c`), mirroring the reference's config-file layering where CLI
    flags win over file values (ref: launch.py:212+,
    runner/common/util/config_parser.py)."""
    import yaml

    with open(args.config_file) as f:
        data = yaml.safe_load(f) or {}
    flat = {}

    def walk(d):
        for k, v in d.items():
            if isinstance(v, dict):
                walk(v)
            else:
                flat[str(k).replace("-", "_")] = v

    walk(data)
    known = {a.dest for a in parser._actions}
    unknown = sorted(set(flat) - known)
    if unknown:
        raise SystemExit(
            f"hvdrun: unknown config-file keys: {', '.join(unknown)}"
        )
    for dest, val in flat.items():
        # Fill only values still at their parser default — an explicit
        # CLI `0` must not be clobbered (0 == False would match a
        # naive None/False sentinel check).
        if getattr(args, dest, None) == parser.get_default(dest):
            setattr(args, dest, val)


def check_build() -> str:
    """Render the framework/backend availability report
    (ref: horovod/runner/launch.py:106-141 check_build — the reference
    prints which extensions and collective backends were compiled in;
    here frameworks are importability probes and backends come from
    common.basics introspection)."""
    import importlib.util

    from .. import __version__
    from ..common import basics

    def chk(v) -> str:
        return "X" if v else " "

    def has(mod: str) -> bool:
        try:
            return importlib.util.find_spec(mod) is not None
        except (ImportError, ValueError):
            return False

    def native_built() -> bool:
        try:
            from ..cc import native

            return native.available()
        except Exception:
            return False

    return (
        f"Horovod-TPU v{__version__}:\n"
        "\n"
        "Available Frameworks:\n"
        f"    [{chk(has('jax'))}] JAX\n"
        f"    [{chk(has('tensorflow'))}] TensorFlow\n"
        f"    [{chk(has('torch'))}] PyTorch\n"
        f"    [{chk(has('mxnet'))}] MXNet\n"
        f"    [{chk(has('keras'))}] Keras\n"
        "\n"
        "Available Controllers:\n"
        f"    [{chk(basics.tcp_built())}] TCP (Gloo equivalent)\n"
        f"    [{chk(basics.mpi_built())}] MPI\n"
        "\n"
        "Available Tensor Operations:\n"
        f"    [{chk(basics.xla_built())}] XLA collectives (ICI/DCN)\n"
        f"    [{chk(basics.tcp_built())}] TCP star/ring/hier-ring\n"
        f"    [{chk(native_built())}] Native C++ reduction kernels\n"
        f"    [{chk(basics.nccl_built())}] NCCL\n"
        f"    [{chk(basics.ddl_built())}] DDL\n"
        f"    [{chk(basics.ccl_built())}] CCL\n"
    )


def run_commandline(argv: Optional[Sequence[str]] = None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)
    if args.check_build:
        print(check_build())
        return 0
    if args.config_file:
        _apply_config_file(parser, args)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("hvdrun: no command given", file=sys.stderr)
        return 2

    extra_env = config_parser.args_to_env(args)

    if args.host_discovery_script or (args.min_np is not None):
        from .elastic.launcher import launch_elastic

        return launch_elastic(args, command, extra_env)

    if args.hostfile:
        hosts = parse_hostfile(args.hostfile)
    elif args.hosts:
        hosts = parse_hosts(args.hosts)
    else:
        # No explicit hosts: auto-detect TPU-VM slice topology (one
        # worker process per pod host; SURVEY.md §5.8 — slice metadata
        # replaces the reference's ssh+NIC probing). Engage only when
        # the requested -np fits the slice (np unset, or one rank per
        # pod host); otherwise keep the historical local launch so
        # `hvdrun -np 4` on a pod worker still runs 4 local processes.
        from .hosts import discover_tpu_hosts

        hosts = discover_tpu_hosts()
        if hosts and args.num_proc not in (None, len(hosts)):
            hosts = None
        if hosts:
            if args.verbose:
                print(f"hvdrun: discovered TPU slice hosts: "
                      f"{','.join(h.hostname for h in hosts)}")
        else:
            np_ = args.num_proc or 1
            hosts = [HostInfo("localhost", np_)]
    np_ = args.num_proc or sum(h.slots for h in hosts)
    slots = get_host_assignments(hosts, np_, np_)
    if args.verbose:
        for s in slots:
            print(f"hvdrun: rank {s.rank} -> {s.hostname} "
                  f"(local {s.local_rank}/{s.local_size})")
    return launch_static(
        slots, command, extra_env, args.verbose,
        prefix_output=not args.disable_output_prefix,
        ssh_port=args.ssh_port, ssh_identity_file=args.ssh_identity_file,
    )


def main():  # console entry point
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
