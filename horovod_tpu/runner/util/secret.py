"""Shared-secret HMAC helpers for the runner's service protocol.

(ref: horovod/runner/common/util/secret.py:21-37 — a per-job 32-byte
secret distributed to workers through their environment; every service
message carries an HMAC-SHA256 digest checked before deserialization.)
"""
from __future__ import annotations

import hashlib
import hmac
import os
from typing import Optional

SECRET_LENGTH = 32  # bytes
DIGEST_LENGTH = 32  # bytes (sha256)

# Env var carrying the hex-encoded per-job secret (the reference ships
# it as _HOROVOD_SECRET_KEY through Open MPI / Spark env plumbing).
SECRET_ENV = "HOROVOD_SECRET_KEY"


def make_secret_key() -> bytes:
    return os.urandom(SECRET_LENGTH)


def compute_digest(key: bytes, message: bytes) -> bytes:
    return hmac.new(key, message, hashlib.sha256).digest()


def check_digest(key: bytes, message: bytes, digest: bytes) -> bool:
    return hmac.compare_digest(compute_digest(key, message), digest)


def key_from_env() -> Optional[bytes]:
    """Decode the job secret from the environment, if set."""
    v = os.environ.get(SECRET_ENV)
    if not v:
        return None
    try:
        return bytes.fromhex(v)
    except ValueError:
        return None


def key_to_env(key: bytes) -> str:
    return key.hex()
