"""Threaded HTTP key-value rendezvous server.

Re-design of the reference's rendezvous KV store
(ref: horovod/runner/http/http_server.py:35-242): workers PUT/GET small
values (socket addresses, rank assignments) under scoped keys; the Gloo-
equivalent TCP backend uses it to build its full mesh, and the elastic
driver uses it to hand out new rank assignments on membership changes
(ref: horovod/runner/elastic/rendezvous.py:28-52).

When constructed with a per-job secret (the launcher generates one and
ships it to workers via HOROVOD_SECRET_KEY), every request must carry an
HMAC-SHA256 digest over ``method\\npath\\nbody`` in the
``X-Horovod-Digest`` header; unauthenticated requests get 403. This
extends the reference's HMAC service protocol (ref: runner/common/util/
network.py:50-110, secret.py:26-34) to the KV store itself, closing the
reference's own gap of an unauthenticated rendezvous.
"""
from __future__ import annotations

import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from .util import secret as secret_util

# Per-job namespace layout (docs/elastic.md "Sharing one rendezvous
# server"): clients with HOROVOD_JOB_NAME set prefix every key with
# jobs/<name>/. The server stays namespace-agnostic for the KV protocol
# itself; the one namespace-aware feature is capacity arbitration —
# jobs declare how many slots they want under
# jobs/<name>/capacity/want, and (with a fleet size configured) the
# server answers with a fair split under jobs/<name>/capacity/grant.
_CAPACITY_WANT_RE = re.compile(r"^jobs/([A-Za-z0-9._-]+)/capacity/want$")


def arbitrate_capacity(wants: Dict[str, int], total: int) -> Dict[str, int]:
    """Max-min fair integer split of ``total`` fleet slots across jobs.

    Water-filling: every unsatisfied job repeatedly receives an equal
    share of what is left, so a small job is fully satisfied before big
    jobs start dividing the surplus. Deterministic — remainders and
    one-slot rounds resolve in job-name order — so every caller
    computes the same grants from the same wants."""
    grants = {j: 0 for j in wants}
    remaining = max(0, total)
    unsat = sorted(j for j, w in wants.items() if w > 0)
    while unsat and remaining > 0:
        share = max(1, remaining // len(unsat))
        nxt = []
        for j in unsat:
            take = min(wants[j] - grants[j], share, remaining)
            grants[j] += take
            remaining -= take
            if grants[j] < wants[j]:
                nxt.append(j)
            if remaining <= 0:
                break
        unsat = nxt
    return grants

# Requests older than this (or from further in the future) are rejected;
# within the window a digest may be accepted only once, so a captured
# PUT/DELETE cannot be replayed (e.g. re-posting a stale rank assignment
# during an elastic re-rendezvous).
REPLAY_WINDOW_S = 300.0


def sign_request(key: bytes, method: str, path: str, body: bytes,
                 ts: Optional[str] = None) -> Tuple[str, str]:
    """Returns (digest_hex, timestamp) for the request headers."""
    if ts is None:
        ts = repr(time.time())
    msg = b"\n".join((method.encode(), path.encode(), ts.encode(), body))
    return secret_util.compute_digest(key, msg).hex(), ts


def _replay_window() -> float:
    """HOROVOD_REPLAY_WINDOW (seconds; 0 disables the timestamp check
    for clusters with known clock skew — replay dedup still applies
    within a run)."""
    try:
        return float(os.environ.get("HOROVOD_REPLAY_WINDOW",
                                    REPLAY_WINDOW_S))
    except ValueError:
        return REPLAY_WINDOW_S


def _check_request(key: bytes, method: str, path: str, body: bytes,
                   digest_hex: Optional[str],
                   ts: Optional[str]) -> Tuple[bool, str]:
    """(ok, reject_reason) — the reason reaches the client so an
    operator can tell clock skew apart from a wrong secret key."""
    if not digest_hex or not ts:
        return False, "missing digest or timestamp header"
    try:
        digest = bytes.fromhex(digest_hex)
        tval = float(ts)
    except ValueError:
        return False, "malformed digest or timestamp"
    window = _replay_window()
    if window > 0 and abs(time.time() - tval) > window:
        # ASCII only: this string travels in an HTTP header (latin-1).
        return False, (
            f"timestamp {abs(time.time() - tval):.0f}s outside the "
            f"{window:.0f}s replay window - check host clocks (NTP) or "
            "raise HOROVOD_REPLAY_WINDOW"
        )
    msg = b"\n".join((method.encode(), path.encode(), ts.encode(), body))
    if not secret_util.check_digest(key, msg, digest):
        return False, "bad digest (HOROVOD_SECRET_KEY mismatch?)"
    return True, ""


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # silence
        pass

    def _key(self) -> str:
        return self.path.lstrip("/")

    def _authorized(self, body: bytes = b"") -> bool:
        server: RendezvousServer = self.server.rendezvous  # type: ignore
        if server.secret_key is None:
            return True
        digest_hex = self.headers.get("X-Horovod-Digest")
        ok, reason = _check_request(
            server.secret_key, self.command, self.path, body,
            digest_hex, self.headers.get("X-Horovod-Timestamp"),
        )
        # A valid digest is single-use within the replay window: GETs
        # are read-only and may retry, but a mutating request replayed
        # verbatim is rejected.
        if ok and self.command in ("PUT", "DELETE") \
                and not server._accept_once(digest_hex):
            ok, reason = False, "replayed request (digest already seen)"
        if not ok:
            self.send_response(403)
            self.send_header("X-Horovod-Reject-Reason", reason)
            self.send_header("Content-Length", "0")
            self.end_headers()
        return ok

    def do_GET(self):
        if not self._authorized():
            return
        server: RendezvousServer = self.server.rendezvous  # type: ignore
        val = server.handle_get(self._key())
        if val is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(len(val)))
            self.end_headers()
            self.wfile.write(val)

    def do_PUT(self):
        server: RendezvousServer = self.server.rendezvous  # type: ignore
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._authorized(body):
            return
        server.handle_put(self._key(), body)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        # Scope finalization (ref: http_server.py RendezvousHandler DELETE)
        if not self._authorized():
            return
        server: RendezvousServer = self.server.rendezvous  # type: ignore
        server.handle_delete(self._key())
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer:
    def __init__(self, verbose: int = 0,
                 secret_key: Optional[bytes] = None,
                 fleet_slots: Optional[int] = None):
        if fleet_slots is None:
            from ..utils import env as env_cfg

            fleet_slots = env_cfg.fleet_slots()
        # >0 enables capacity arbitration between per-job namespaces;
        # 0 (the default) keeps the server a plain KV store.
        self.fleet_slots = fleet_slots
        self.secret_key = secret_key
        self._store: Dict[str, bytes] = {}
        self._seen_digests: Dict[str, float] = {}
        self._evict_warned = False
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Optional hook for elastic rank reassignment
        # (key -> value or None to fall through to the store).
        self.get_hook: Optional[Callable[[str], Optional[bytes]]] = None
        self.put_hook: Optional[Callable[[str, bytes], None]] = None

    def start(self, port: int = 0) -> int:
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), _KVHandler)
        self._httpd.rendezvous = self  # type: ignore
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rendezvous", daemon=True
        )
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> int:
        assert self._httpd is not None
        return self._httpd.server_address[1]

    def _accept_once(self, digest_hex: str) -> bool:
        """True the first time a digest is seen inside the window."""
        now = time.time()
        with self._lock:
            window = _replay_window()
            if window <= 0:
                # Window disabled: time-based eviction would never fire
                # (cutoff -inf), so bound the dedup dict by count
                # instead, evicting oldest-first. Tradeoff: an attacker
                # who can push >64Ki PUTs between a capture and its
                # replay defeats dedup — but timestamps are unverifiable
                # under a disabled window anyway, and unbounded growth
                # is a guaranteed DoS on long-lived servers.
                if len(self._seen_digests) >= 65536 \
                        and not self._evict_warned:
                    self._evict_warned = True
                    logging.getLogger("horovod_tpu.runner").warning(
                        "rendezvous replay dedup reached its 64Ki cap "
                        "with HOROVOD_REPLAY_WINDOW disabled; evicting "
                        "oldest digests — dedup is best-effort from here"
                    )
                while len(self._seen_digests) >= 65536:
                    del self._seen_digests[next(iter(self._seen_digests))]
            elif len(self._seen_digests) > 4096:
                # Never evict inside the ACTIVE window: with a raised
                # HOROVOD_REPLAY_WINDOW, pruning at the default 300s
                # would re-open the replay hole the dedup exists to
                # close.
                cutoff = now - max(window, REPLAY_WINDOW_S)
                for d in [d for d, t in self._seen_digests.items()
                          if t < cutoff]:
                    del self._seen_digests[d]
            if digest_hex in self._seen_digests:
                return False
            self._seen_digests[digest_hex] = now
            return True

    def handle_get(self, key: str) -> Optional[bytes]:
        if self.get_hook is not None:
            v = self.get_hook(key)
            if v is not None:
                return v
        with self._lock:
            return self._store.get(key)

    def handle_put(self, key: str, value: bytes):
        if self.put_hook is not None:
            self.put_hook(key, value)
        with self._lock:
            self._store[key] = value
        if self.fleet_slots > 0 and _CAPACITY_WANT_RE.match(key):
            self._arbitrate()

    def _arbitrate(self):
        """Recompute per-job capacity grants from every declared want.
        Runs on each want-update; grants land in the store so any job
        (or the elasticity controller) reads its budget with a plain
        GET on jobs/<name>/capacity/grant."""
        with self._lock:
            wants: Dict[str, int] = {}
            for k, v in self._store.items():
                m = _CAPACITY_WANT_RE.match(k)
                if m is None:
                    continue
                try:
                    wants[m.group(1)] = max(0, int(v.decode()))
                except (ValueError, UnicodeDecodeError):
                    wants[m.group(1)] = 0
            grants = arbitrate_capacity(wants, self.fleet_slots)
            for j, g in grants.items():
                self._store[f"jobs/{j}/capacity/grant"] = str(g).encode()

    def handle_delete(self, key: str):
        with self._lock:
            prefix = key.rstrip("/") + "/"
            for k in [k for k in self._store if k == key or k.startswith(prefix)]:
                del self._store[k]

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
