"""Threaded HTTP key-value rendezvous server.

Re-design of the reference's rendezvous KV store
(ref: horovod/runner/http/http_server.py:35-242): workers PUT/GET small
values (socket addresses, rank assignments) under scoped keys; the Gloo-
equivalent TCP backend uses it to build its full mesh, and the elastic
driver uses it to hand out new rank assignments on membership changes
(ref: horovod/runner/elastic/rendezvous.py:28-52).
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # silence
        pass

    def _key(self) -> str:
        return self.path.lstrip("/")

    def do_GET(self):
        server: RendezvousServer = self.server.rendezvous  # type: ignore
        val = server.handle_get(self._key())
        if val is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(len(val)))
            self.end_headers()
            self.wfile.write(val)

    def do_PUT(self):
        server: RendezvousServer = self.server.rendezvous  # type: ignore
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        server.handle_put(self._key(), body)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        # Scope finalization (ref: http_server.py RendezvousHandler DELETE)
        server: RendezvousServer = self.server.rendezvous  # type: ignore
        server.handle_delete(self._key())
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer:
    def __init__(self, verbose: int = 0):
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Optional hook for elastic rank reassignment
        # (key -> value or None to fall through to the store).
        self.get_hook: Optional[Callable[[str], Optional[bytes]]] = None
        self.put_hook: Optional[Callable[[str, bytes], None]] = None

    def start(self, port: int = 0) -> int:
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), _KVHandler)
        self._httpd.rendezvous = self  # type: ignore
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rendezvous", daemon=True
        )
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> int:
        assert self._httpd is not None
        return self._httpd.server_address[1]

    def handle_get(self, key: str) -> Optional[bytes]:
        if self.get_hook is not None:
            v = self.get_hook(key)
            if v is not None:
                return v
        with self._lock:
            return self._store.get(key)

    def handle_put(self, key: str, value: bytes):
        if self.put_hook is not None:
            self.put_hook(key, value)
        with self._lock:
            self._store[key] = value

    def handle_delete(self, key: str):
        with self._lock:
            prefix = key.rstrip("/") + "/"
            for k in [k for k in self._store if k == key or k.startswith(prefix)]:
                del self._store[k]

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
