"""Authenticated driver/task RPC services.

TPU-native re-design of the reference's tiny service protocol
(ref: horovod/runner/common/util/network.py:50-180 Wire/BasicService/
BasicClient; common/service/task_service.py BasicTaskService;
common/service/driver_service.py BasicDriverService): pickled
request/response objects over TCP, each message prefixed by an
HMAC-SHA256 digest computed with a per-job shared secret. The digest is
verified BEFORE unpickling, so an unauthenticated peer can never reach
the deserializer — the property the reference's HMAC layer provides.

What it is used for here:
  * the driver runs a ``DriverService``; each worker host's
    ``TaskService`` registers with it (replacing the reference's
    NIC-probe ring — TPU-VM slices are fully routed, so registration
    only carries addresses);
  * the driver can execute commands on worker hosts through an
    authenticated channel (``TaskClient.run_command``) instead of
    trusting bare ssh for every exec, and collect exit codes.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from .util import secret as secret_util

logger = get_logger()

_LEN = struct.Struct("<I")


# ---------------------------------------------------------------------------
# Request/response objects (ref: network.py PingRequest/PingResponse/
# AckResponse; task_service.py RunCommandRequest etc.)
class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name: str, source_address: str):
        self.service_name = service_name
        self.source_address = source_address


class AckResponse:
    pass


class ErrorResponse:
    """Handler-side failure echoed to the caller (the reference lets the
    exception kill the connection; an explicit error is kinder)."""

    def __init__(self, message: str):
        self.message = message


class RegisterTaskRequest:
    def __init__(self, index: int, addresses: Dict[str, int], hostname: str):
        self.index = index
        self.addresses = addresses
        self.hostname = hostname


class AllTaskAddressesRequest:
    pass


class AllTaskAddressesResponse:
    def __init__(self, all_task_addresses: Dict[int, Dict[str, int]]):
        self.all_task_addresses = all_task_addresses


class RunCommandRequest:
    def __init__(self, command: List[str], env: Dict[str, str]):
        self.command = command
        self.env = env


class CommandExitCodeRequest:
    def __init__(self, output_offset: int = 0):
        # The caller's high-water mark: only output[offset:] comes back,
        # so steady polling is O(new bytes), not O(total bytes).
        self.output_offset = output_offset


class CommandExitCodeResponse:
    def __init__(self, terminated: bool, exit_code: Optional[int],
                 output: bytes, output_offset: int = 0):
        self.terminated = terminated
        self.exit_code = exit_code
        self.output = output          # delta starting at output_offset
        self.output_offset = output_offset


class TerminateRequest:
    pass


class ShutdownServiceRequest:
    """Stop the service process itself (the launcher sends this at job
    teardown so remote bootstraps exit instead of leaking — killing the
    local ssh client alone does not signal the remote command)."""


class AuthError(RuntimeError):
    """Digest verification failed."""


# ---------------------------------------------------------------------------
class Wire:
    """digest(32) + length(4) + pickled body; digest checked before any
    unpickle (ref: network.py:50-84).

    The length header is attacker-controlled and read before the digest
    can be verified, so it is capped: control-plane messages are small
    (requests, env dicts, short output chunks), and without a cap an
    unauthenticated peer could force multi-GiB allocations on services
    that bind 0.0.0.0."""

    MAX_MESSAGE_BYTES = 16 * 1024 * 1024

    def __init__(self, key: bytes):
        if not key:
            raise ValueError("service protocol requires a non-empty key")
        self._key = key

    def write(self, obj: Any, wfile):
        body = pickle.dumps(obj)
        if len(body) > self.MAX_MESSAGE_BYTES:
            # Fail at the sender with an actionable message — the
            # receiver would otherwise reject the frame as a misleading
            # AuthError on the remote side.
            raise ValueError(
                f"message of {len(body)} bytes exceeds the "
                f"{self.MAX_MESSAGE_BYTES}-byte wire cap; control-plane "
                "messages must stay small (ship bulk data out of band)"
            )
        wfile.write(secret_util.compute_digest(self._key, body))
        wfile.write(_LEN.pack(len(body)))
        wfile.write(body)
        wfile.flush()

    def read(self, rfile) -> Any:
        digest = self._read_exact(rfile, secret_util.DIGEST_LENGTH)
        (n,) = _LEN.unpack(self._read_exact(rfile, 4))
        if n > self.MAX_MESSAGE_BYTES:
            raise AuthError(f"frame of {n} bytes exceeds the "
                            f"{self.MAX_MESSAGE_BYTES}-byte message cap")
        body = self._read_exact(rfile, n)
        if not secret_util.check_digest(self._key, body, digest):
            raise AuthError("digest did not match the message")
        return pickle.loads(body)

    @staticmethod
    def _read_exact(rfile, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = rfile.read(n - len(buf))
            if not chunk:
                raise EOFError("peer closed connection")
            buf.extend(chunk)
        return bytes(buf)


# ---------------------------------------------------------------------------
class BasicService:
    """Threaded TCP server speaking the authenticated Wire protocol
    (ref: network.py BasicService)."""

    def __init__(self, service_name: str, key: bytes):
        self.service_name = service_name
        self._wire = Wire(key)
        handler = self._make_handler()
        self._server = socketserver.ThreadingTCPServer(
            ("0.0.0.0", 0), handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self.shutdown_requested = threading.Event()
        self._port = self._server.socket.getsockname()[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=service_name, daemon=True
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def addresses(self) -> Dict[str, int]:
        return {socket.gethostname(): self._port}

    def _make_handler(self):
        service = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    req = service._wire.read(self.rfile)
                except AuthError:
                    # Unauthenticated peer: drop without a response (the
                    # reference raises inside the handler; either way no
                    # object is ever deserialized).
                    logger.warning(
                        "%s: rejected message with bad digest from %s",
                        service.service_name, self.client_address[0],
                    )
                    return
                except (EOFError, ConnectionError):
                    return
                try:
                    resp = service._handle(req, self.client_address)
                except Exception as e:  # noqa: BLE001
                    logger.error("%s: handler error: %s",
                                 service.service_name, e)
                    resp = ErrorResponse(f"{type(e).__name__}: {e}")
                try:
                    service._wire.write(resp, self.wfile)
                except (BrokenPipeError, ConnectionError):
                    pass

        return _Handler

    def _handle(self, req: Any, client_address: Tuple[str, int]) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse(self.service_name, client_address[0])
        if isinstance(req, ShutdownServiceRequest):
            self.shutdown_requested.set()
            return AckResponse()
        raise NotImplementedError(
            f"{self.service_name}: unknown request {type(req).__name__}"
        )

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class BasicClient:
    def __init__(self, addr: str, port: int, key: bytes,
                 timeout: float = 30.0):
        self._addr = addr
        self._port = port
        self._wire = Wire(key)
        self._timeout = timeout

    def _send(self, req: Any) -> Any:
        with socket.create_connection(
            (self._addr, self._port), timeout=self._timeout
        ) as s:
            rfile = s.makefile("rb")
            wfile = s.makefile("wb")
            self._wire.write(req, wfile)
            resp = self._wire.read(rfile)
        if isinstance(resp, ErrorResponse):
            raise RuntimeError(
                f"{type(req).__name__} failed on the service: {resp.message}"
            )
        return resp

    def ping(self) -> PingResponse:
        return self._send(PingRequest())

    def shutdown_service(self):
        self._send(ShutdownServiceRequest())


# ---------------------------------------------------------------------------
class TaskService(BasicService):
    """Per-host worker-side service: executes driver-issued commands and
    reports their exit (ref: common/service/task_service.py
    BasicTaskService.RunCommand/CommandExitCode)."""

    def __init__(self, index: int, key: bytes):
        super().__init__(f"task-{index}", key)
        self.index = index
        self._proc: Optional[subprocess.Popen] = None
        self._output = bytearray()
        self._proc_lock = threading.Lock()

    def _handle(self, req, client_address):
        if isinstance(req, RunCommandRequest):
            with self._proc_lock:
                if self._proc is not None and self._proc.poll() is None:
                    raise RuntimeError("a command is already running")
                import os

                env = dict(os.environ)
                env.update(req.env)
                self._output = bytearray()
                self._proc = subprocess.Popen(
                    req.command, env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, start_new_session=True,
                )
                t = threading.Thread(
                    target=self._pump, args=(self._proc,), daemon=True
                )
                t.start()
            return AckResponse()
        if isinstance(req, CommandExitCodeRequest):
            with self._proc_lock:
                p = self._proc
                rc = None if p is None else p.poll()
                off = min(getattr(req, "output_offset", 0),
                          len(self._output))
                return CommandExitCodeResponse(
                    terminated=(p is not None and rc is not None),
                    exit_code=rc,
                    output=bytes(self._output[off:]),
                    output_offset=off,
                )
        if isinstance(req, TerminateRequest):
            with self._proc_lock:
                if self._proc is not None and self._proc.poll() is None:
                    self._proc.terminate()
            return AckResponse()
        return super()._handle(req, client_address)

    def _pump(self, proc: subprocess.Popen):
        for line in iter(proc.stdout.readline, b""):
            self._output.extend(line)
        proc.stdout.close()
        proc.wait()


class TaskClient(BasicClient):
    def run_command(self, command: List[str],
                    env: Optional[Dict[str, str]] = None):
        self._send(RunCommandRequest(command, env or {}))

    def command_exit_code(self, output_offset: int = 0) -> CommandExitCodeResponse:
        return self._send(CommandExitCodeRequest(output_offset))

    def wait_for_command(self, timeout: float = 300.0) -> Tuple[int, bytes]:
        deadline = time.monotonic() + timeout
        collected = bytearray()
        while time.monotonic() < deadline:
            r = self.command_exit_code(len(collected))
            collected.extend(r.output)
            if r.terminated:
                return r.exit_code, bytes(collected)
            time.sleep(0.1)
        raise TimeoutError("command did not finish")

    def terminate(self):
        self._send(TerminateRequest())


# ---------------------------------------------------------------------------
class DriverService(BasicService):
    """Driver-side registration service: collects every task's service
    addresses so the driver can reach workers without re-ssh
    (ref: common/service/driver_service.py BasicDriverService)."""

    def __init__(self, num_tasks: int, key: bytes):
        super().__init__("driver", key)
        self._num_tasks = num_tasks
        self._tasks: Dict[int, Dict[str, int]] = {}
        self._hostnames: Dict[int, str] = {}
        self._all_registered = threading.Event()
        self._reg_lock = threading.Lock()

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskRequest):
            with self._reg_lock:
                self._tasks[req.index] = req.addresses
                self._hostnames[req.index] = req.hostname
                if len(self._tasks) == self._num_tasks:
                    self._all_registered.set()
            return AckResponse()
        if isinstance(req, AllTaskAddressesRequest):
            return AllTaskAddressesResponse(dict(self._tasks))
        return super()._handle(req, client_address)

    def wait_for_all_tasks(self, timeout: float = 120.0) -> Dict[int, Dict[str, int]]:
        if not self._all_registered.wait(timeout):
            missing = set(range(self._num_tasks)) - set(self._tasks)
            raise TimeoutError(f"tasks never registered: {sorted(missing)}")
        return dict(self._tasks)

    def task_hostname(self, index: int) -> Optional[str]:
        return self._hostnames.get(index)


class DriverClient(BasicClient):
    def register_task(self, index: int, addresses: Dict[str, int],
                      hostname: str):
        self._send(RegisterTaskRequest(index, addresses, hostname))

    def all_task_addresses(self) -> Dict[int, Dict[str, int]]:
        return self._send(AllTaskAddressesRequest()).all_task_addresses
