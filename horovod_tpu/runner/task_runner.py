"""Worker-side entry for `run(func)` mode: load the pickled function,
execute it under hvd, PUT the pickled result to the rendezvous KV
(ref: horovod/runner/launch.py:552-574 --run-func result collection)."""
from __future__ import annotations

import pickle
import sys


def main(func_path: str):
    import os

    # CPU-only workers unless the user's function sets up devices itself.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with open(func_path, "rb") as f:
        fn = pickle.load(f)

    import horovod_tpu as hvd
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.utils import env as env_cfg

    result = fn()

    client = RendezvousClient(
        env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR, "127.0.0.1"),
        env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0),
    )
    rank = env_cfg.get_int(env_cfg.RANK, 0)
    client.put("results", str(rank), pickle.dumps(result))


if __name__ == "__main__":
    main(sys.argv[1])
