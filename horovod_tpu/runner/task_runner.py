"""Worker-side entries.

* `python -m horovod_tpu.runner.task_runner <func.pkl>` — `run(func)`
  mode: load the pickled function, execute it under hvd, PUT the
  pickled result to the rendezvous KV (ref: horovod/runner/
  launch.py:552-574 --run-func result collection).
* `python -m horovod_tpu.runner.task_runner --task-service --index I
  --driver HOST:PORT` — host a per-slot authenticated TaskService and
  register it with the launcher's DriverService; the launcher then
  starts the worker command through the HMAC RPC (ref: runner/
  task_fn.py + common/service/task_service.py bootstrap flow).
"""
from __future__ import annotations

import pickle
import sys


def task_service_main(index: int, driver: str):
    import os

    from .service import DriverClient, TaskService
    from .util import secret as secret_util

    key = secret_util.key_from_env()
    if key is None:
        print("task_runner: HOROVOD_SECRET_KEY is required for "
              "--task-service", file=sys.stderr)
        return 2
    svc = TaskService(index=index, key=key)
    host, port = driver.rsplit(":", 1)
    DriverClient(host, int(port), key).register_task(
        index, {os.uname().nodename: svc.port}, os.uname().nodename
    )
    # Serve until the launcher sends ShutdownServiceRequest (killing the
    # local ssh client would NOT stop this remote process — without a
    # pty sshd leaves the command running, so an explicit RPC is the
    # teardown path) or the process group is signalled.
    svc.shutdown_requested.wait()
    svc.shutdown()
    return 0


def main(func_path: str):
    # The launcher (runner.run) owns the worker platform policy and
    # always sets JAX_PLATFORMS in the worker env.
    with open(func_path, "rb") as f:
        fn = pickle.load(f)

    import horovod_tpu as hvd
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.utils import env as env_cfg

    result = fn()

    client = RendezvousClient(
        env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR, "127.0.0.1"),
        env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0),
    )
    rank = env_cfg.get_int(env_cfg.RANK, 0)
    client.put("results", str(rank), pickle.dumps(result))


if __name__ == "__main__":
    if "--task-service" in sys.argv:
        import argparse

        p = argparse.ArgumentParser()
        p.add_argument("--task-service", action="store_true")
        p.add_argument("--index", type=int, required=True)
        p.add_argument("--driver", required=True)
        args = p.parse_args()
        sys.exit(task_service_main(args.index, args.driver) or 0)
    main(sys.argv[1])
