"""Worker state registry: tracks per-slot READY/SUCCESS/FAILURE and
drives the reset decision.

(ref: horovod/runner/elastic/registration.py — barrier over world size;
on completion: stop on success or all-failure, blacklist failed hosts,
enforce reset_limit, else driver.resume().)
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ...utils.logging import get_logger

logger = get_logger()

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, reset_limit: Optional[int] = None):
        self._driver = driver
        self._hosts = host_manager
        self._lock = threading.Condition()
        self._states: Dict[str, str] = {}      # "host:local_rank" -> state
        self._reset_count = 0
        self._reset_limit = reset_limit
        self._world: int = 0
        self._expected: Optional[set] = None
        self._epoch = 0
        # One barrier action per epoch: a late verdict landing after the
        # barrier already fired (e.g. the watchdog evicted a slot AND
        # its process then died) must not re-run blacklist/resume.
        self._acted = False

    def reset(self, world_size: int, expected=None):
        """New epoch: expect `world_size` verdicts before acting
        (ref: registration.py:56 barrier resize). `expected` (a set of
        "host:local_rank" keys) scopes the barrier: verdicts for keys
        outside it are dropped — a worker evicted in the previous epoch
        whose process dies a beat later must not count toward (or
        instantly trip) the new, smaller barrier."""
        with self._lock:
            self._states = {}
            self._world = world_size
            self._expected = set(expected) if expected is not None else None
            self._epoch += 1
            self._acted = False

    def verdicts(self) -> Dict[str, str]:
        """Snapshot of this epoch's recorded verdicts (the ready-timeout
        watchdog diffs it against the assignments to find the slots that
        never answered)."""
        with self._lock:
            return dict(self._states)

    @property
    def epoch(self) -> int:
        """Barrier-epoch token: capture it with a verdict snapshot and
        pass it back to record() so a verdict computed against one
        barrier can never pollute the next one (the eviction path races
        the evicted worker's own exit monitor — whichever fires the
        barrier first resets the epoch, and the loser's record must be
        dropped)."""
        with self._lock:
            return self._epoch

    @property
    def reset_count(self) -> int:
        return self._reset_count

    def record(self, key: str, state: str, epoch: Optional[int] = None):
        """Record a slot's verdict; the last verdict triggers the barrier
        action (ref: registration.py:113-172). `epoch` (from the
        `epoch` property) makes the record conditional on the barrier it
        was computed against."""
        opener = None
        opener_token = 0
        fire: Optional[Dict[str, str]] = None
        with self._lock:
            if self._driver.finished:
                return
            if epoch is not None and epoch != self._epoch:
                return  # stale verdict from a barrier that already fired
            if self._expected is not None and key not in self._expected:
                return  # slot not part of this epoch's barrier
            opened = not self._states
            self._states[key] = state
            logger.debug("worker %s -> %s (%d/%d)", key, state,
                         len(self._states), self._world)
            if opened:
                # First verdict of the epoch: the barrier is collecting.
                # The driver arms the ready-deadline watchdog so a slot
                # that never answers (wedged worker) is evicted and the
                # barrier is guaranteed to fire (docs/elastic.md
                # "Recovery-time guarantees").
                opener = getattr(self._driver, "_on_barrier_opened", None)
                # Token captured under the lock: the hook runs outside
                # it and may be delayed past this barrier's resolution —
                # the driver must know WHICH barrier it belongs to.
                opener_token = self._epoch
            if len(self._states) >= self._world and not self._acted:
                self._acted = True
                fire = dict(self._states)
        # Driver callouts run OUTSIDE the registry lock: the barrier
        # action takes the driver lock (finish/resume), and the driver's
        # eviction paths take the driver lock before querying the
        # registry (epoch/verdicts) — calling out while holding this
        # lock is an AB-BA deadlock between the watchdog timer and the
        # evicted worker's exit monitor.
        if opener is not None:
            opener(opener_token)
        if fire is not None:
            self._barrier_action(fire)

    def record_ready(self, host: str, local_rank: int):
        self.record(f"{host}:{local_rank}", READY)

    def record_success(self, host: str, local_rank: int):
        self.record(f"{host}:{local_rank}", SUCCESS)

    def record_failure(self, host: str, local_rank: int,
                       epoch: Optional[int] = None):
        self.record(f"{host}:{local_rank}", FAILURE, epoch=epoch)

    # ------------------------------------------------------------------
    def _barrier_action(self, states: Dict[str, str]):
        succeeded = [k for k, v in states.items() if v == SUCCESS]
        failed = [k for k, v in states.items() if v == FAILURE]

        if succeeded and len(succeeded) == len(states):
            self._driver.finish(0)
            return
        if failed and len(failed) == len(states):
            logger.error("all workers failed; stopping job")
            self._driver.finish(1)
            return
        # Partial failure → blacklist failing hosts and resume with the
        # survivors (ref: registration.py:132-172). Each host once per
        # barrier: N failed slots on one host are ONE failure for the
        # cooldown-escalation ladder, or a single bad epoch on a
        # multi-slot host would jump straight to permanent.
        for host in {key.rsplit(":", 1)[0] for key in failed}:
            self._hosts.blacklist(host)
        with self._lock:
            self._reset_count += 1
        if self._reset_limit is not None and self._reset_count > self._reset_limit:
            logger.error(
                "reset limit %d exceeded; stopping job", self._reset_limit
            )
            self._driver.finish(1)
            return
        self._driver.resume()
