"""Worker state registry: tracks per-slot READY/SUCCESS/FAILURE and
drives the reset decision.

(ref: horovod/runner/elastic/registration.py — barrier over world size;
on completion: stop on success or all-failure, blacklist failed hosts,
enforce reset_limit, else driver.resume().)
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ...utils.logging import get_logger

logger = get_logger()

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, reset_limit: Optional[int] = None):
        self._driver = driver
        self._hosts = host_manager
        self._lock = threading.Condition()
        self._states: Dict[str, str] = {}      # "host:local_rank" -> state
        self._reset_count = 0
        self._reset_limit = reset_limit
        self._world: int = 0
        self._epoch = 0

    def reset(self, world_size: int):
        """New epoch: expect `world_size` verdicts before acting
        (ref: registration.py:56 barrier resize)."""
        with self._lock:
            self._states = {}
            self._world = world_size
            self._epoch += 1

    @property
    def reset_count(self) -> int:
        return self._reset_count

    def record(self, key: str, state: str):
        """Record a slot's verdict; the last verdict triggers the barrier
        action (ref: registration.py:113-172)."""
        with self._lock:
            if self._driver.finished:
                return
            self._states[key] = state
            logger.debug("worker %s -> %s (%d/%d)", key, state,
                         len(self._states), self._world)
            if len(self._states) >= self._world:
                self._barrier_action()

    def record_ready(self, host: str, local_rank: int):
        self.record(f"{host}:{local_rank}", READY)

    def record_success(self, host: str, local_rank: int):
        self.record(f"{host}:{local_rank}", SUCCESS)

    def record_failure(self, host: str, local_rank: int):
        self.record(f"{host}:{local_rank}", FAILURE)

    # ------------------------------------------------------------------
    def _barrier_action(self):
        states = dict(self._states)
        succeeded = [k for k, v in states.items() if v == SUCCESS]
        failed = [k for k, v in states.items() if v == FAILURE]

        if succeeded and len(succeeded) == len(states):
            self._driver.finish(0)
            return
        if failed and len(failed) == len(states):
            logger.error("all workers failed; stopping job")
            self._driver.finish(1)
            return
        # Partial failure → blacklist failing hosts and resume with the
        # survivors (ref: registration.py:132-172).
        for key in failed:
            host = key.rsplit(":", 1)[0]
            self._hosts.blacklist(host)
        self._reset_count += 1
        if self._reset_limit is not None and self._reset_count > self._reset_limit:
            logger.error(
                "reset limit %d exceeded; stopping job", self._reset_limit
            )
            self._driver.finish(1)
            return
        self._driver.resume()
