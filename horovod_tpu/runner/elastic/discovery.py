"""Host discovery for elastic training.

(ref: horovod/runner/elastic/discovery.py — HostDiscoveryScript runs a
user script that prints `hostname[:slots]` lines; HostManager keeps a
stable host ordering (oldest first) and a blacklist.)
"""
from __future__ import annotations

import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from ...utils import env as env_cfg
from ...utils.logging import get_logger

logger = get_logger()


class HostUpdateResult:
    NO_UPDATE = 0
    REMOVED = 1
    ADDED = 2
    MIXED = REMOVED | ADDED


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """hostname → slots."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """(ref: discovery.py:130-152)"""

    def __init__(self, discovery_script: str, slots: Optional[int] = None):
        self.script = discovery_script
        self.default_slots = slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(
            self.script, shell=True, timeout=60
        ).decode()
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts[name] = int(slots)
            else:
                if self.default_slots is None:
                    raise ValueError(
                        f"discovery line {line!r} has no slot count and no "
                        "--slots-per-host default was given"
                    )
                hosts[line] = self.default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """(ref: discovery.py FixedHosts)"""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def set(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Stable-ordered view of available hosts with blacklisting
    (ref: discovery.py:79-121 — order preserves host age so rank 0 stays
    on the oldest surviving host, which carries state through resets).

    Blacklisting is cooldown-with-escalation
    (docs/fault_tolerance.md): a host's FIRST failure parks it for
    ``HOROVOD_BLACKLIST_COOLDOWN_SECONDS`` (a transient flake — OOM
    blip, network hiccup — gets another chance once the storm passes),
    a REPEAT failure parks it permanently. The reference's forever-set
    semantics are available via cooldown 0."""

    def __init__(self, discovery: HostDiscovery,
                 cooldown: Optional[float] = None):
        self._discovery = discovery
        self._order: List[str] = []          # first-seen order
        self._current: Dict[str, int] = {}
        # host -> blacklist expiry (monotonic; inf = permanent)
        self._blacklist: Dict[str, float] = {}
        # host -> drain-quarantine expiry (monotonic). Strike-free
        # sibling of the blacklist for ANNOUNCED preemptions.
        self._quarantine: Dict[str, float] = {}
        self._strikes: Dict[str, int] = {}
        self._cooldown = (env_cfg.blacklist_cooldown_seconds()
                          if cooldown is None else cooldown)
        self._lock = threading.Lock()

    def _active_blacklist(self) -> set:
        """Prune expired cooldowns; call with the lock held."""
        now = time.monotonic()
        for h in [h for h, exp in self._blacklist.items() if exp <= now]:
            del self._blacklist[h]
            logger.warning(
                "blacklist cooldown expired for host %s; it is eligible "
                "again (a repeat failure will blacklist it permanently)",
                h)
        return set(self._blacklist)

    def update_available_hosts(self) -> int:
        new = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            # The previous view must be filtered with the blacklist AS
            # IT WAS, before pruning expired cooldowns: a host whose
            # cooldown just lapsed is absent from prev_active and
            # present in active, i.e. an ADDED update — otherwise the
            # recovered host is invisible (NO_UPDATE) and a driver
            # parked on "not enough slots" never re-assigns.
            prev_excluded = set(self._blacklist) | set(self._quarantine)
            excluded = self._active_blacklist() | self._active_quarantine()
            prev_active = {
                h: s for h, s in self._current.items()
                if h not in prev_excluded
            }
            res = HostUpdateResult.NO_UPDATE
            for h in new:
                if h not in self._order:
                    self._order.append(h)
            active = {h: s for h, s in new.items() if h not in excluded}
            if set(active) - set(prev_active) or any(
                active.get(h, 0) > prev_active.get(h, 0) for h in active
            ):
                res |= HostUpdateResult.ADDED
            if set(prev_active) - set(active) or any(
                active.get(h, 0) < prev_active.get(h, 0)
                for h in prev_active if h in active
            ):
                res |= HostUpdateResult.REMOVED
            self._current = new
            return res

    @property
    def current_hosts(self) -> List[Tuple[str, int]]:
        """Active (hostname, slots), oldest first."""
        with self._lock:
            blacklist = self._active_blacklist()
            quarantined = self._active_quarantine()
            return [
                (h, self._current[h])
                for h in self._order
                if h in self._current and h not in blacklist
                and h not in quarantined and self._current[h] > 0
            ]

    def _active_quarantine(self) -> set:
        """Prune expired quarantines; call with the lock held."""
        now = time.monotonic()
        for h in [h for h, exp in self._quarantine.items() if exp <= now]:
            del self._quarantine[h]
            logger.info("drain quarantine expired for host %s; it is "
                        "eligible again", h)
        return set(self._quarantine)

    def quarantine(self, host: str, seconds: float):
        """Temporarily exclude a DRAINING host from assignment
        (docs/fault_tolerance.md "Announced preemption"). Deliberately
        NOT the blacklist: a drain is intentional, so it must cost the
        host no failure strikes and never escalate to permanent — the
        platform usually takes the machine away anyway, and if it
        survives the quarantine it is welcome back."""
        with self._lock:
            expiry = time.monotonic() + max(seconds, 0.0)
            self._quarantine[host] = max(
                expiry, self._quarantine.get(host, 0.0))
            logger.warning("quarantining draining host %s for %.0fs",
                           host, max(seconds, 0.0))
        from ...common import events as events_mod

        events_mod.emit(events_mod.HOST_QUARANTINE,
                        severity=events_mod.WARN, rank=-1, host=host,
                        seconds=round(max(seconds, 0.0), 1))

    def is_quarantined(self, host: str) -> bool:
        with self._lock:
            return host in self._active_quarantine()

    def blacklist(self, host: str):
        from ...common import telemetry

        with self._lock:
            self._strikes[host] = strikes = self._strikes.get(host, 0) + 1
            if strikes > 1 or self._cooldown <= 0:
                expiry, how = float("inf"), "permanently"
            else:
                expiry = time.monotonic() + self._cooldown
                how = f"for {self._cooldown:.0f}s (first failure)"
            already = self._blacklist.get(host)
            self._blacklist[host] = max(expiry, already or 0.0)
            if already is None:
                logger.warning("blacklisting host %s %s", host, how)
                telemetry.counter(
                    "horovod_hosts_blacklisted_total",
                    "Hosts blacklisted after worker failures",
                ).inc()
                from ...common import events as events_mod

                events_mod.emit(events_mod.HOST_BLACKLIST,
                                severity=events_mod.ERROR, rank=-1,
                                host=host, strikes=strikes,
                                permanent=expiry == float("inf"))

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return host in self._active_blacklist()

    def blacklist_strikes(self, host: str) -> int:
        with self._lock:
            return self._strikes.get(host, 0)

    def available_slots(self) -> int:
        return sum(s for _, s in self.current_hosts)
