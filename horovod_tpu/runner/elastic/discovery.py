"""Host discovery for elastic training.

(ref: horovod/runner/elastic/discovery.py — HostDiscoveryScript runs a
user script that prints `hostname[:slots]` lines; HostManager keeps a
stable host ordering (oldest first) and a blacklist.)
"""
from __future__ import annotations

import subprocess
import threading
from typing import Dict, List, Optional, Tuple

from ...utils.logging import get_logger

logger = get_logger()


class HostUpdateResult:
    NO_UPDATE = 0
    REMOVED = 1
    ADDED = 2
    MIXED = REMOVED | ADDED


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """hostname → slots."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """(ref: discovery.py:130-152)"""

    def __init__(self, discovery_script: str, slots: Optional[int] = None):
        self.script = discovery_script
        self.default_slots = slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(
            self.script, shell=True, timeout=60
        ).decode()
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts[name] = int(slots)
            else:
                if self.default_slots is None:
                    raise ValueError(
                        f"discovery line {line!r} has no slot count and no "
                        "--slots-per-host default was given"
                    )
                hosts[line] = self.default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """(ref: discovery.py FixedHosts)"""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def set(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Stable-ordered view of available hosts with blacklisting
    (ref: discovery.py:79-121 — order preserves host age so rank 0 stays
    on the oldest surviving host, which carries state through resets)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._order: List[str] = []          # first-seen order
        self._current: Dict[str, int] = {}
        self._blacklist: set = set()
        self._lock = threading.Lock()

    def update_available_hosts(self) -> int:
        new = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            res = HostUpdateResult.NO_UPDATE
            prev_active = {
                h: s for h, s in self._current.items()
                if h not in self._blacklist
            }
            for h in new:
                if h not in self._order:
                    self._order.append(h)
            active = {h: s for h, s in new.items() if h not in self._blacklist}
            if set(active) - set(prev_active) or any(
                active.get(h, 0) > prev_active.get(h, 0) for h in active
            ):
                res |= HostUpdateResult.ADDED
            if set(prev_active) - set(active) or any(
                active.get(h, 0) < prev_active.get(h, 0)
                for h in prev_active if h in active
            ):
                res |= HostUpdateResult.REMOVED
            self._current = new
            return res

    @property
    def current_hosts(self) -> List[Tuple[str, int]]:
        """Active (hostname, slots), oldest first."""
        with self._lock:
            return [
                (h, self._current[h])
                for h in self._order
                if h in self._current and h not in self._blacklist
                and self._current[h] > 0
            ]

    def blacklist(self, host: str):
        with self._lock:
            if host not in self._blacklist:
                logger.warning("blacklisting host %s", host)
                self._blacklist.add(host)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return host in self._blacklist

    def available_slots(self) -> int:
        return sum(s for _, s in self.current_hosts)
