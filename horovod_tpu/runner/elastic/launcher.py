"""Elastic launch path for hvdrun (--min-np/--max-np/
--host-discovery-script), wiring ElasticDriver + RendezvousServer +
worker subprocesses (ref: horovod/runner/gloo_run.py:274-309
launch_gloo_elastic).
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Sequence

from ...utils import env as env_cfg
from ..launch import is_local_host, slot_env, spawn_worker
from ..rendezvous_server import RendezvousServer
from .discovery import FixedHosts, HostDiscoveryScript
from .driver import ElasticDriver


def launch_elastic(args, command: Sequence[str],
                   extra_env: Dict[str, str]) -> int:
    if args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        args.slots_per_host)
    elif args.hosts:
        from ..hosts import parse_hosts

        discovery = FixedHosts({
            h.hostname: h.slots for h in parse_hosts(args.hosts)
        })
    else:
        print("hvdrun: elastic mode needs --host-discovery-script or -H",
              file=sys.stderr)
        return 2

    np_ = args.num_proc or args.min_np or 1
    min_np = args.min_np or np_
    max_np = args.max_np or args.num_proc

    from ..util import secret as secret_util

    server = RendezvousServer(secret_key=secret_util.make_secret_key())
    port = server.start()
    driver = ElasticDriver(
        server, discovery, min_np=min_np, max_np=max_np,
        reset_limit=args.reset_limit,
    )

    def create_worker(slot, worker_extra_env):
        env = slot_env(slot, "127.0.0.1" if is_local_host(slot.hostname)
                       else _driver_addr(), port, extra_env, elastic=True,
                       secret_key=server.secret_key)
        env.update(worker_extra_env)
        handle = spawn_worker(
            slot, list(command), env,
            verbose=args.verbose,
            prefix_output=not getattr(args, "disable_output_prefix", False),
            ssh_port=args.ssh_port, ssh_identity_file=args.ssh_identity_file,
        )
        return handle.proc

    # Goodput-driven elasticity (docs/elastic.md "The elasticity
    # controller"): off unless HOROVOD_CONTROLLER_INTERVAL_SECONDS > 0.
    from .controller import ElasticityController

    controller = ElasticityController(driver)

    try:
        driver.start(create_worker)
        controller.start()
        code = driver.wait()
        return code if code is not None else 1
    finally:
        controller.stop()
        driver.stop()
        server.stop()


def _driver_addr() -> str:
    import socket

    return os.environ.get("HVDRUN_DRIVER_ADDR") or socket.gethostname()
