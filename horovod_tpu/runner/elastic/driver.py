"""Elastic driver: discovery polling, stable rank reassignment, worker
lifecycle (ref: horovod/runner/elastic/driver.py:30-308).

Topology changes are versioned by an **epoch**. Each activation the
driver publishes, into the rendezvous KV:

    rank_and_size_e<E>/<host>:<spawn_local_rank> -> "rank,size,..." rows
        (INVALID row = the worker lost its slot and should exit;
         ref: gloo_context.cc:157-200 rank==-1 contract)
    meta/epoch -> E        (written last: epoch visible ⇒ rows complete)

Workers re-initializing (elastic_env.refresh_topology_from_rendezvous)
announce `ready_e<E>/<key>`, wait for a newer epoch, then read their row.
The epoch also scopes the TCP full-mesh bootstrap (HOROVOD_MESH_SCOPE)
so a re-formed mesh never sees stale peer addresses.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...common import telemetry
from ...common import events as events_mod
from ...common.health import VERDICT_KEY_PREFIX, decode_verdict
from ...utils import env as env_cfg
from ...utils.logging import get_logger
from ..hosts import HostInfo, SlotInfo, get_host_assignments
from ..rendezvous_server import RendezvousServer
from .discovery import HostManager, HostUpdateResult
from .registration import FAILURE, READY, WorkerStateRegistry

logger = get_logger()

INVALID_ROW = "-1,-1,-1,-1,-1,-1"
READY_PREFIX = "ready_e"
DRAIN_PREFIX = "drain_e"


class _WorkerRecord:
    def __init__(self, key: Tuple[str, int], proc):
        self.key = key
        self.proc = proc
        self.thread: Optional[threading.Thread] = None


class ElasticDriver:
    def __init__(
        self,
        rendezvous: RendezvousServer,
        discovery,
        min_np: int,
        max_np: Optional[int] = None,
        reset_limit: Optional[int] = None,
        poll_interval: Optional[float] = None,
    ):
        if poll_interval is None:
            # 1s default (ref: driver.py:30); tests shrink it via env.
            poll_interval = env_cfg.get_float(
                "HOROVOD_ELASTIC_DISCOVERY_INTERVAL", 1.0
            )
        self.rendezvous = rendezvous
        self.host_manager = HostManager(discovery)
        self.registry = WorkerStateRegistry(self, self.host_manager,
                                            reset_limit)
        self.min_np = min_np
        self.max_np = max_np
        self.poll_interval = poll_interval
        self.epoch = -1
        # The driver process has no MESH_SCOPE env: stamp its lifecycle
        # events (remesh/join/evict, quarantines, controller decisions)
        # with the live mesh epoch so the fleet chronicle's causal sort
        # interleaves them with the workers' (docs/events.md).
        events_mod.set_epoch_provider(
            lambda: self.epoch if self.epoch >= 0 else None)
        self._create_worker: Optional[Callable] = None
        self._workers: Dict[Tuple[str, int], _WorkerRecord] = {}
        self._assignments: Dict[Tuple[str, int], SlotInfo] = {}
        self._lock = threading.RLock()
        self._finished = threading.Event()
        self.exit_code: Optional[int] = None
        self._discovery_thread: Optional[threading.Thread] = None
        # Bounded-time recovery (docs/elastic.md "Recovery-time
        # guarantees"): every epoch's reset barrier gets a deadline — a
        # slot with no verdict by then is evicted (killed + recorded
        # failed) so the barrier ALWAYS fires and survivors re-mesh
        # instead of parking forever behind a wedged worker.
        self._ready_timeout = env_cfg.elastic_ready_timeout()
        # Watchdog state has its own leaf lock: the arm path is called
        # from the registry's record path (first verdict of an epoch)
        # on arbitrary threads — worker exit monitors, the rendezvous
        # put hook — and stays off the driver lock so it can never
        # participate in a lock-ordering cycle.
        self._watchdog_lock = threading.Lock()
        self._watchdog: Optional[threading.Timer] = None
        # Registry-epoch token the armed watchdog was captured against
        # (see _on_barrier_opened): identifies WHICH barrier the
        # deadline belongs to, so a hook delayed past that barrier's
        # resolution can never arm a deadline against the next one.
        self._watchdog_token: Optional[int] = None
        # First failure evidence of the current incident; observed into
        # the recovery-duration histogram when the next activation
        # completes (failure -> re-meshed).
        self._failure_t0: Optional[float] = None
        # Drain plane (docs/fault_tolerance.md "Announced preemption"):
        # slots whose worker ANNOUNCED a drain. Their exits are
        # intentional (never failures, never blacklist strikes), their
        # liveness verdicts are moot, and the notice -> re-meshed
        # window gets its own histogram.
        self._draining: Dict[Tuple[str, int], float] = {}
        self._drain_t0: Optional[float] = None
        # Per-job KV namespace: the driver prefixes every key it
        # publishes/reads exactly like namespaced RendezvousClients do,
        # so a trainer and a server job can share one server
        # (docs/elastic.md "Sharing one rendezvous server").
        self._ns = env_cfg.job_kv_prefix()
        self._m_evictions = telemetry.counter(
            "horovod_elastic_evictions_total",
            "Reset-barrier slots evicted at the ready deadline "
            "(worker killed, recorded as failed)")
        self._m_recovery = telemetry.histogram(
            "horovod_elastic_recovery_seconds",
            "Failure detection to re-meshed activation", min_exp=-4,
            max_exp=10)
        self._m_drain = telemetry.histogram(
            "horovod_drain_evict_seconds",
            "Drain notice to re-meshed activation (the announced-"
            "preemption fast path — no liveness timeout)", min_exp=-4,
            max_exp=10)
        rendezvous.put_hook = self._observe_put

    def _put(self, key: str, value: bytes):
        self.rendezvous.handle_put(f"{self._ns}{key}", value)

    def _get(self, key: str):
        return self.rendezvous.handle_get(f"{self._ns}{key}")

    # ------------------------------------------------------------------
    @property
    def finished(self) -> bool:
        return self._finished.is_set()

    def finish(self, code: int):
        with self._lock:
            if not self._finished.is_set():
                self.exit_code = code
                self._finished.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        self._finished.wait(timeout)
        return self.exit_code

    # ------------------------------------------------------------------
    def start(self, create_worker: Callable):
        """create_worker(slot: SlotInfo, extra_env: dict) -> Popen."""
        self._create_worker = create_worker
        self._announce_resume_point()
        self.wait_for_available_slots(self.min_np)
        self._activate()
        self._discovery_thread = threading.Thread(
            target=self._discover_loop, name="elastic-discovery", daemon=True
        )
        self._discovery_thread.start()

    def _announce_resume_point(self):
        """Kill-all-job recovery, driver side (docs/checkpoint.md): at
        (re)start, discover the newest COMPLETE checkpoint manifest in
        HOROVOD_CHECKPOINT_DIR and publish it to the rendezvous KV
        (``ckpt/resume``) — observability for operators and a
        cross-check for workers, which perform the actual shard loads
        from shared storage in `hvd.elastic.run` before their first
        step. No checkpoint dir (or no manifest) = a fresh job."""
        root = env_cfg.checkpoint_dir()
        if not root:
            return
        from ...common import checkpoint as ckpt

        found = ckpt.find_latest_manifest(root)
        if found is None:
            logger.info("no complete checkpoint under %s; starting fresh",
                        root)
            return
        step, manifest, _ = found
        logger.info(
            "job will resume from checkpoint step %d (%d shards, "
            "written at world size %d)", step, len(manifest["shards"]),
            manifest["world_size"])
        import json as _json

        self._put(
            f"{ckpt.LATEST_SCOPE}/{ckpt.RESUME_KEY}",
            _json.dumps({"step": step,
                         "world_size": manifest["world_size"]}).encode())

    def wait_for_available_slots(self, min_np: int, timeout: float = 600.0):
        """(ref: driver.py:145 wait_for_available_slots)"""
        deadline = time.monotonic() + timeout
        while True:
            self.host_manager.update_available_hosts()
            if self.host_manager.available_slots() >= min_np:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {min_np} slots; available: "
                    f"{self.host_manager.current_hosts}"
                )
            time.sleep(self.poll_interval)

    # ------------------------------------------------------------------
    def _discover_loop(self):
        """(ref: driver.py:176-195 — poll every second)"""
        while not self._finished.is_set():
            time.sleep(self.poll_interval)
            try:
                res = self.host_manager.update_available_hosts()
            except Exception as e:  # discovery script hiccup
                logger.warning("host discovery failed: %s", e)
                continue
            if res != HostUpdateResult.NO_UPDATE and not self._finished.is_set():
                if self.host_manager.available_slots() < self.min_np:
                    logger.warning(
                        "hosts dropped below min_np=%d; waiting", self.min_np
                    )
                    continue
                logger.info("host changes detected (%d); re-assigning", res)
                self._activate(notify_update=res)

    # ------------------------------------------------------------------
    def resume(self):
        """Post-failure reactivation (ref: registration.py barrier action
        → driver.resume)."""
        if self.host_manager.available_slots() >= self.min_np:
            self._activate()
        else:
            # Stay parked; discovery loop reactivates once enough hosts
            # return.
            logger.warning("resume deferred: not enough slots")

    def _activate(self, notify_update: int = 0):
        with self._lock:
            if self._finished.is_set():
                return
            hosts = [
                HostInfo(h, s) for h, s in self.host_manager.current_hosts
            ]
            slots = get_host_assignments(
                hosts, self.min_np, self.max_np
            )
            self.epoch += 1
            new_assignments: Dict[Tuple[str, int], SlotInfo] = {
                (s.hostname, s.local_rank): s for s in slots
            }

            # Publish rows: assigned slots + INVALID rows for live workers
            # that lost their slot; epoch key LAST.
            scope = f"rank_and_size_e{self.epoch}"
            for (host, idx), slot in new_assignments.items():
                self._put(
                    f"{scope}/{host}:{idx}", slot.to_response_string().encode()
                )
            for key in self._workers:
                if key not in new_assignments:
                    self._put(
                        f"{scope}/{key[0]}:{key[1]}", INVALID_ROW.encode()
                    )
            self._put("meta/epoch", str(self.epoch).encode())
            self._assignments = new_assignments

            # Spawn processes for slots with no live worker.
            self._prune_dead_workers()
            for key, slot in new_assignments.items():
                if key not in self._workers:
                    self._spawn(key, slot)

            # The previous epoch's barrier is resolved; its deadline (if
            # any) is moot. Cancel BEFORE the registry reset: a verdict
            # recorded in the gap would see the stale timer as "already
            # armed" and skip arming the new epoch's deadline.
            self._cancel_watchdog()
            self.registry.reset(
                len(new_assignments),
                expected={f"{h}:{i}" for (h, i) in new_assignments})
            if self._failure_t0 is not None:
                # Failure -> re-meshed: rows published, survivors
                # spawned/notified, new barrier armed.
                self._m_recovery.observe(
                    time.monotonic() - self._failure_t0)
                self._failure_t0 = None
            # Drained slots that lost their assignment are evicted for
            # good: close out the notice -> re-meshed window.
            for key in [k for k in self._draining
                        if k not in new_assignments]:
                del self._draining[key]
            if self._drain_t0 is not None and not self._draining:
                self._m_drain.observe(time.monotonic() - self._drain_t0)
                self._drain_t0 = None
            events_mod.emit(events_mod.ELASTIC_REMESH, rank=-1,
                            mesh_epoch=self.epoch,
                            world=len(new_assignments),
                            hosts=len({h for h, _ in new_assignments}))
        if notify_update:
            self._notify_workers(notify_update)

    def _note_failure(self):
        with self._lock:
            if self._failure_t0 is None:
                self._failure_t0 = time.monotonic()

    # ------------------------------------------------------------------
    def _on_barrier_opened(self, reg_epoch: int):
        """First verdict of a registry epoch landed: that barrier is now
        collecting, so give it a deadline. Called from the registry's
        record path on arbitrary threads — keeps to its own leaf lock.

        `reg_epoch` is the registry epoch captured under the registry
        lock when the opening verdict was recorded. The hook runs after
        that lock is released, so it can be delayed past the barrier's
        own resolution (all remaining verdicts land, _activate resets
        the registry) — armed naively, its timer would then expire
        against the NEXT epoch's untouched barrier and evict every
        healthy worker. The token makes that impossible: a stale timer
        is inert at fire time (token no longer matches the registry)
        and is replaced outright when the new barrier really opens."""
        if self._ready_timeout <= 0 or self._finished.is_set():
            return
        with self._watchdog_lock:
            if self._watchdog is not None:
                if self._watchdog_token == reg_epoch:
                    return  # already armed for this barrier
                self._watchdog.cancel()  # stale timer from a lost race
            t = threading.Timer(self._ready_timeout,
                                self._evict_stragglers, args=(reg_epoch,))
            t.daemon = True
            t.name = f"elastic-watchdog-r{reg_epoch}"
            self._watchdog = t
            self._watchdog_token = reg_epoch
            t.start()

    def _cancel_watchdog(self):
        with self._watchdog_lock:
            if self._watchdog is not None:
                self._watchdog.cancel()
                self._watchdog = None
                self._watchdog_token = None

    def _evict_stragglers(self, reg_epoch: int):
        """Ready-deadline eviction: every assigned slot with no verdict
        (READY/SUCCESS/FAILURE) after HOROVOD_ELASTIC_READY_TIMEOUT is
        killed and recorded as failed, so the barrier fires, the wedged
        host is blacklisted (it failed — the reporters recorded READY),
        and survivors re-mesh."""
        with self._watchdog_lock:
            if self._watchdog_token != reg_epoch:
                return  # superseded (or cancelled) while firing
            self._watchdog = None  # this timer just fired
            self._watchdog_token = None
        with self._lock:
            if self._finished.is_set() or reg_epoch != self.registry.epoch:
                return  # that barrier already resolved
            verdicts = self.registry.verdicts()
            # Draining slots are exempt: their silence is expected (the
            # worker is checkpointing, then exiting) and the drain path
            # owns their eviction.
            missing = [k for k in self._assignments
                       if f"{k[0]}:{k[1]}" not in verdicts
                       and k not in self._draining]
            if not missing:
                return
            stragglers = []
            for key in missing:
                rec = self._workers.get(key)
                stragglers.append((key, rec))
        for key, rec in stragglers:
            host, idx = key
            logger.error(
                "evicting worker %s:%d: no verdict %.0fs after the reset "
                "barrier opened (HOROVOD_ELASTIC_READY_TIMEOUT)",
                host, idx, self._ready_timeout)
            self._m_evictions.inc()
            events_mod.emit(events_mod.ELASTIC_EVICT,
                            severity=events_mod.ERROR, rank=-1,
                            host=host, slot=idx, reason="ready_timeout")
            self._note_failure()
            if rec is not None and rec.proc.poll() is None:
                try:
                    rec.proc.kill()
                except OSError:  # pragma: no cover - already gone
                    pass
            # Record the failure directly (epoch-guarded: the killed
            # worker's own exit monitor may fire the barrier first, and
            # this record must then be dropped, not pollute the next
            # epoch): even a kill-proof wedge or an already-reaped
            # process must not hold the barrier.
            self.registry.record_failure(host, idx, epoch=reg_epoch)

    def _prune_dead_workers(self):
        for key in [k for k, w in self._workers.items()
                    if w.proc.poll() is not None]:
            del self._workers[key]

    def _spawn(self, key: Tuple[str, int], slot: SlotInfo):
        extra_env = {
            env_cfg.ELASTIC: "1",
            env_cfg.MESH_SCOPE: f"hvd_mesh_e{self.epoch}",
            "HOROVOD_SPAWN_LOCAL_RANK": str(slot.local_rank),
        }
        # Durability knobs travel with the slot: a create_worker that
        # builds a minimal env from slot_env (rather than inheriting
        # os.environ) must still give every worker the same checkpoint
        # plane the driver discovered its resume point from.
        import os as _os

        for var in (env_cfg.CHECKPOINT_DIR, env_cfg.CHECKPOINT_INTERVAL,
                    env_cfg.CHECKPOINT_KEEP,
                    env_cfg.CHECKPOINT_COMMIT_TIMEOUT,
                    env_cfg.CHECKPOINT_FSYNC,
                    # Drain/namespace plane: workers must agree with the
                    # driver on the preempt signal, the grace budget,
                    # and the per-job KV prefix.
                    env_cfg.DRAIN_GRACE_SECONDS, env_cfg.PREEMPT_SIGNAL,
                    env_cfg.JOB_NAME):
            if var in _os.environ:
                extra_env[var] = _os.environ[var]
        events_mod.emit(events_mod.ELASTIC_JOIN, rank=-1,
                        host=key[0], slot=key[1], worker_rank=slot.rank,
                        mesh_epoch=self.epoch)
        proc = self._create_worker(slot, extra_env)
        rec = _WorkerRecord(key, proc)
        rec.thread = threading.Thread(
            target=self._monitor, args=(rec,), daemon=True,
            name=f"worker-{key[0]}:{key[1]}",
        )
        self._workers[key] = rec
        rec.thread.start()

    def _monitor(self, rec: _WorkerRecord):
        """Wait for process exit; record the verdict
        (ref: driver.py worker exit handling + registration)."""
        rc = rec.proc.wait()
        if self._finished.is_set():
            return
        host, idx = rec.key
        with self._lock:
            cur = self._workers.get(rec.key)
            if cur is rec:
                del self._workers[rec.key]
            # A stale process (superseded after an eviction/respawn) or
            # an unassigned slot must not feed the CURRENT epoch's
            # barrier — its verdict belongs to a previous incident.
            stale = cur is not rec
            assigned = rec.key in self._assignments
            draining = rec.key in self._draining
        if rc == 0 or draining:
            # A draining worker's exit is the PLAN even when nonzero
            # (killed past its grace window): success, no strike.
            if assigned and not stale:
                self.registry.record_success(host, idx)
            # else: worker exited after an INVALID row — expected.
        else:
            logger.warning("worker %s:%d exited with %d", host, idx, rc)
            if assigned and not stale:
                self._note_failure()
                self.registry.record_failure(host, idx)

    # ------------------------------------------------------------------
    def _observe_put(self, key: str, value: bytes):
        """Rendezvous put hook: READY announcements from resetting
        workers feed the registry barrier, and liveness verdicts from
        the coordinator worker's heartbeat monitor trigger the eviction
        fast path — the driver blacklists the host that FAILED (named
        in the verdict), not the host that reported it, and does not
        have to wait out the full ready deadline. Drain notices from
        preempted workers take the same fast path: quarantine + planned
        eviction with no liveness timeout at all. With a job namespace
        set, only keys in OUR namespace are interpreted — a co-tenant
        job's protocol traffic is someone else's."""
        if self._ns:
            if not key.startswith(self._ns):
                return
            key = key[len(self._ns):]
        if key.startswith(DRAIN_PREFIX):
            epoch_part, _, ident = key[len(DRAIN_PREFIX):].partition("/")
            try:
                epoch = int(epoch_part)
            except ValueError:
                return
            if ident and ident != "any":
                self._on_drain_notice(epoch, ident)
            return
        if key.startswith(VERDICT_KEY_PREFIX):
            try:
                epoch = int(key[len(VERDICT_KEY_PREFIX):])
            except ValueError:
                return
            parsed = decode_verdict(value)
            if parsed is None:
                return
            dead_rank, host, reason = parsed
            self._on_liveness_verdict(epoch, dead_rank, host, reason)
            return
        if key.startswith(READY_PREFIX):
            epoch_part, _, ident = key[len(READY_PREFIX):].partition("/")
            try:
                epoch = int(epoch_part)
            except ValueError:
                return
            if not ident:
                return
            # Registry token BEFORE the driver-epoch check: if _activate
            # runs between them the check goes stale-and-fails; if it
            # runs after, the token mismatch drops the record — either
            # way a late READY (e.g. from a worker the watchdog already
            # evicted) can never count toward the NEXT epoch's barrier.
            reg_epoch = self.registry.epoch
            if epoch == self.epoch:
                host, _, idx = ident.rpartition(":")
                try:
                    self.registry.record(
                        f"{host}:{int(idx)}", READY, epoch=reg_epoch)
                except ValueError:
                    pass

    def _on_liveness_verdict(self, epoch: int, dead_rank: int, host: str,
                             reason: str):
        with self._lock:
            if self._finished.is_set() or epoch != self.epoch:
                return  # stale verdict from a pre-reset mesh
            reg_epoch = self.registry.epoch
            target = None
            for key, slot in self._assignments.items():
                if slot.rank == dead_rank:
                    target = (key, self._workers.get(key))
                    break
        if target is None:
            return
        (thost, idx), rec = target
        with self._lock:
            if (thost, idx) in self._draining:
                # The worker announced a drain; its heartbeats stopping
                # is the PLAN, not a failure — the drain path owns the
                # eviction and the host must collect no strike.
                return
        already = self.registry.verdicts().get(f"{thost}:{idx}")
        if already == FAILURE:
            return
        logger.error("liveness verdict for rank %d (%s:%d): %s — evicting",
                     dead_rank, thost, idx, reason)
        self._m_evictions.inc()
        events_mod.emit(events_mod.ELASTIC_EVICT,
                        severity=events_mod.ERROR, rank=-1,
                        host=thost, slot=idx, worker_rank=dead_rank,
                        reason="liveness_verdict")
        self._note_failure()
        if rec is not None and rec.proc.poll() is None:
            try:
                rec.proc.kill()
            except OSError:  # pragma: no cover - already gone
                pass
        self.registry.record_failure(thost, idx, epoch=reg_epoch)

    def _on_drain_notice(self, epoch: int, ident: str):
        """A worker announced a preemption drain (common/drain.py
        publishes drain_e<epoch>/<identity> the moment the notice
        lands). The announced-preemption fast path: quarantine the host
        (strike-free), then evict on the worker's own clean exit —
        no liveness timeout is ever waited out."""
        host, _, idx_s = ident.rpartition(":")
        try:
            idx = int(idx_s)
        except ValueError:
            return
        grace = env_cfg.drain_grace_seconds()
        key = (host, idx)
        with self._lock:
            if self._finished.is_set() or epoch != self.epoch:
                return  # stale notice from a pre-reset mesh
            if key not in self._assignments:
                return
            if key in self._draining:
                return  # "requested" then "drained": one eviction
            self._draining[key] = time.monotonic()
            if self._drain_t0 is None:
                self._drain_t0 = time.monotonic()
            rec = self._workers.get(key)
        logger.warning(
            "drain notice from %s:%d: quarantining host, re-mesh on its "
            "exit (announced preemption — no liveness timeout)", host, idx)
        # Cover grace + re-mesh; a host the platform did NOT take away
        # becomes eligible again afterwards (scale-up readds it).
        self.host_manager.quarantine(host, max(grace * 2.0, 60.0))
        t = threading.Thread(target=self._drain_evict, args=(key, rec),
                             daemon=True, name=f"drain-{host}:{idx}")
        t.start()

    def _drain_evict(self, key: Tuple[str, int], rec):
        """Wait out the drained worker's clean exit (bounded by grace +
        margin; kill past it — the platform would have), then
        re-activate so survivors re-mesh against the shrunk world."""
        grace = env_cfg.drain_grace_seconds()
        if rec is not None:
            try:
                rec.proc.wait(timeout=grace + 10.0)
            except Exception:
                logger.error(
                    "drained worker %s:%d outlived its grace window; "
                    "killing it", key[0], key[1])
                try:
                    rec.proc.kill()
                except OSError:  # pragma: no cover - already gone
                    pass
        with self._lock:
            if self._finished.is_set():
                return
            if key not in self._assignments:
                return  # an activation already re-meshed without it
        if self.host_manager.available_slots() < self.min_np:
            logger.warning(
                "drain of %s:%d leaves fewer than min_np=%d slots; "
                "waiting for discovery to find replacements",
                key[0], key[1], self.min_np)
            return
        events_mod.emit(events_mod.ELASTIC_EVICT,
                        severity=events_mod.WARN, rank=-1,
                        host=key[0], slot=key[1], reason="drain")
        self._activate(notify_update=HostUpdateResult.REMOVED)

    def _notify_workers(self, update_res: int):
        """Ping every live worker's notification endpoint
        (ref: runner/elastic/worker.py HostsUpdatedRequest)."""
        import http.client

        ts = time.time()
        with self._lock:
            keys = list(self._workers)
        for host, idx in keys:
            addr = self._get(f"workers_notify/{host}:{idx}")
            if addr is None:
                continue
            h, _, p = addr.decode().rpartition(":")
            try:
                c = http.client.HTTPConnection(h or "127.0.0.1", int(p),
                                               timeout=5)
                c.request("PUT", "/hosts_updated", body=f"{ts},{update_res}")
                c.getresponse().read()
                c.close()
            except OSError as e:
                logger.debug("notify %s:%s failed: %s", host, idx, e)

    # ------------------------------------------------------------------
    def stop(self):
        self.finish(self.exit_code if self.exit_code is not None else 1)
        self._cancel_watchdog()
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            if w.proc.poll() is None:
                try:
                    w.proc.terminate()
                except OSError:
                    pass
        # Teardown reuses the drain protocol's grace budget: workers see
        # SIGTERM as a preemption notice and may be mid-checkpoint, so
        # give them the same window before escalating to SIGKILL.
        grace = max(10.0, env_cfg.drain_grace_seconds())
        for w in workers:
            try:
                w.proc.wait(timeout=grace)
            except Exception:
                try:
                    w.proc.kill()
                except OSError:
                    pass
