"""Elastic launcher subsystem (ref: horovod/runner/elastic/)."""
from .discovery import (
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
    HostUpdateResult,
)
from .driver import ElasticDriver
from .registration import WorkerStateRegistry
