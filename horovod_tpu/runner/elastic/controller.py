"""Goodput-driven elasticity controller (docs/elastic.md "The
elasticity controller").

A driver-side loop that closes the observability loop the rest of the
stack already publishes into the rendezvous KV: the goodput stamp
mirror (``goodput/status``, common/goodput.py), the coordinator's
fleet-alert mirror (``alerts/fleet``, engine/engine.py), and — when
several jobs share one rendezvous server — the capacity grant the
server arbitrates under ``capacity/grant`` (runner/rendezvous_server.py).
From those plus the driver's own liveness view it makes one of three
calls per tick:

    scale_up    idle capacity exists and the grant allows it — resume()
                the driver so the next activation folds the slots in.
    scale_down  the capacity grant shrank below the current world, or a
                persistently alert-firing straggler rank is dragging
                fleet goodput — hand the victim worker a *preemption
                notice* (the configured drain signal), so the shrink
                rides the graceful-drain path: checkpoint-now,
                announced eviction, no failure strike, no liveness
                timeout.
    hold        anything else (including: a drain already in flight —
                the drain path owns that re-mesh).

Decisions are rate-limited by a cooldown (3 ticks) so one bad stamp
cannot flap the mesh, counted per decision kind
(``horovod_controller_decisions_total``), and mirrored to the KV at
``controller/last`` for operators. ``decide()`` is pure — the whole
policy is unit-testable without a driver (tests/test_preemption.py).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from ...common import telemetry
from ...utils import env as env_cfg
from ...utils.logging import get_logger

logger = get_logger()

SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"


class CooldownGate:
    """The shared rate limit on mesh-changing decisions: after any
    non-HOLD action fires, further actions are vetoed (forced to HOLD)
    until `cooldown` seconds pass. One bad sample must not flap the
    mesh — both this controller and the serving autoscaler
    (serving/autoscaler.py) gate through it."""

    def __init__(self, cooldown: float):
        self.cooldown = max(float(cooldown), 0.0)
        self._last_action_mono: Optional[float] = None

    def veto(self, now: Optional[float] = None) -> bool:
        if self._last_action_mono is None:
            return False
        now = time.monotonic() if now is None else now
        return now - self._last_action_mono < self.cooldown

    def fired(self, now: Optional[float] = None):
        self._last_action_mono = (time.monotonic()
                                  if now is None else now)

# A straggler eviction needs evidence, not one noisy tick: the same
# rank must be named by the alert mirror on this many consecutive
# controller ticks before it is drained out.
STRAGGLER_STRIKES = 3

# Alert rules whose firing names a rank as a straggler worth shrinking
# around (the stall/exposure families; an allreduce-latency alert names
# a symptom, not a culprit).
STRAGGLER_RULES = ("stall", "straggler", "slow_rank", "exposed")


def decide(*, current_np: int, min_np: int, max_np: Optional[int],
           available_slots: int, grant: Optional[int] = None,
           straggler_rank: Optional[int] = None,
           fleet_draining: bool = False) -> Tuple[str, int, str]:
    """Pure policy: (action, target_np, reason).

    Precedence: an in-flight drain freezes everything; then the
    capacity grant (an outside authority) binds in both directions;
    then straggler eviction; then opportunistic growth."""
    if fleet_draining:
        return (HOLD, current_np,
                "drain in flight; the drain path owns the re-mesh")
    cap = max_np if max_np is not None else available_slots
    if grant is not None:
        cap = min(cap, grant)
        if grant < current_np and max(grant, min_np) < current_np:
            return (SCALE_DOWN, max(grant, min_np),
                    f"capacity grant {grant} below current world "
                    f"{current_np}")
    if straggler_rank is not None and current_np - 1 >= min_np:
        return (SCALE_DOWN, current_np - 1,
                f"rank {straggler_rank} named straggler for "
                f"{STRAGGLER_STRIKES} consecutive ticks")
    target = min(available_slots, cap)
    if target > current_np:
        return (SCALE_UP, target,
                f"{available_slots} slots available, world is "
                f"{current_np}")
    return (HOLD, current_np, "steady state")


class ElasticityController:
    """Periodic decide-and-act loop around an ElasticDriver."""

    def __init__(self, driver, interval: Optional[float] = None):
        self.driver = driver
        self.interval = (env_cfg.controller_interval_seconds()
                         if interval is None else interval)
        self.cooldown = self.interval * 3.0
        self._gate = CooldownGate(self.cooldown)
        self._ns = env_cfg.job_kv_prefix()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Last (action, target, reason) journaled — decisions are
        # events only when they CHANGE (docs/events.md).
        self._last_published: Optional[tuple] = None
        # rank -> consecutive ticks it was named by a straggler rule
        self._strikes: Dict[int, int] = {}
        self._m = {
            d: telemetry.counter(
                "horovod_controller_decisions_total",
                "Elasticity controller decisions by kind",
                labels={"decision": d})
            for d in (SCALE_UP, SCALE_DOWN, HOLD)
        }

    # -- KV readings ---------------------------------------------------
    def _kv_json(self, key: str) -> Optional[dict]:
        raw = self.driver.rendezvous.handle_get(f"{self._ns}{key}")
        if raw is None:
            return None
        try:
            return json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None

    def _read_grant(self) -> Optional[int]:
        if not self._ns:
            return None  # capacity arbitration is a namespaced feature
        raw = self.driver.rendezvous.handle_get(f"{self._ns}capacity/grant")
        if raw is None:
            return None
        try:
            return int(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None

    def _straggler_from_alerts(self) -> Optional[int]:
        """A rank is a straggler when a stall-family alert names it for
        STRAGGLER_STRIKES consecutive ticks; one clean tick clears its
        strikes (a transient blip must not cost a machine)."""
        doc = self._kv_json("alerts/fleet") or {}
        named = set()
        for rule, ranks in (doc.get("firing_by_rule") or {}).items():
            if any(s in rule for s in STRAGGLER_RULES):
                named.update(int(r) for r in ranks)
        self._strikes = {r: self._strikes.get(r, 0) + 1 for r in named}
        ripe = [r for r, n in self._strikes.items()
                if n >= STRAGGLER_STRIKES]
        return min(ripe) if ripe else None

    # -- act -----------------------------------------------------------
    def tick(self) -> Tuple[str, int, str]:
        """One observe→decide→act round; returns the decision."""
        drv = self.driver
        with drv._lock:
            current_np = len(drv._assignments)
            draining = bool(drv._draining)
        available = drv.host_manager.available_slots()
        grant = self._read_grant()
        straggler = self._straggler_from_alerts()
        action, target, reason = decide(
            current_np=current_np, min_np=drv.min_np, max_np=drv.max_np,
            available_slots=available, grant=grant,
            straggler_rank=straggler, fleet_draining=draining)
        now = time.monotonic()
        if action != HOLD and self._gate.veto(now):
            action, target, reason = (
                HOLD, current_np,
                f"cooldown ({self.cooldown:.0f}s) after the last action")
        self._m[action].inc()
        self._publish(action, target, current_np, reason)
        if action == HOLD:
            return action, target, reason
        self._gate.fired(now)
        logger.warning("elasticity controller: %s %d -> %d (%s)",
                       action, current_np, target, reason)
        if action == SCALE_UP:
            drv.resume()
        else:
            self._drain_out(current_np - target, straggler)
        return action, target, reason

    def _drain_out(self, count: int, straggler_rank: Optional[int]):
        """Shrink by handing workers the preemption signal — the SAME
        notice the platform would send, so the whole graceful-drain
        machinery (checkpoint-now, announced eviction, quarantine
        without strikes) does the rest. Victims: the named straggler
        first, then the highest ranks (the ones a shrink renumbers
        away anyway)."""
        drv = self.driver
        sig = env_cfg.preempt_signal()
        with drv._lock:
            by_rank = sorted(
                ((slot.rank, key) for key, slot in drv._assignments.items()
                 if key not in drv._draining),
                reverse=True)
            victims = []
            if straggler_rank is not None:
                victims = [(r, k) for r, k in by_rank
                           if r == straggler_rank]
            for r, k in by_rank:
                if len(victims) >= count:
                    break
                if (r, k) not in victims:
                    victims.append((r, k))
            recs = [(r, k, drv._workers.get(k)) for r, k in victims]
        for rank, key, rec in recs:
            if rec is None or rec.proc.poll() is not None:
                continue
            self._strikes.pop(rank, None)
            logger.warning(
                "elasticity controller: sending preemption notice "
                "(signal %d) to rank %d (%s:%d)", sig, rank, *key)
            try:
                rec.proc.send_signal(sig)
            except OSError as e:  # pragma: no cover - already gone
                logger.warning("preempt signal to %s:%d failed: %s",
                               key[0], key[1], e)

    def _publish(self, action: str, target: int, current_np: int,
                 reason: str):
        # Journal the decision (docs/events.md) — but only on CHANGE:
        # a steady HOLD re-published every tick is one fact, not a
        # stream, and must not wash real incidents out of the ring.
        if (action, target, reason) != self._last_published:
            self._last_published = (action, target, reason)
            from ...common import events as events_mod

            events_mod.emit(events_mod.CONTROLLER_DECISION,
                            severity=(events_mod.INFO if action == HOLD
                                      else events_mod.WARN),
                            rank=-1, action=action,
                            current_np=current_np, target_np=target,
                            reason=reason)
        try:
            self.driver.rendezvous.handle_put(
                f"{self._ns}controller/last",
                json.dumps({
                    "wall": time.time(), "action": action,
                    "current_np": current_np, "target_np": target,
                    "reason": reason,
                }, separators=(",", ":")).encode())
        except Exception:  # pragma: no cover - observability only
            pass

    # -- lifecycle -----------------------------------------------------
    def start(self):
        if self.interval <= 0 or self._thread is not None:
            return
        # Declare our appetite so a shared server can arbitrate: want
        # max_np (or min_np when uncapped — a modest ask beats hogging).
        if self._ns:
            want = self.driver.max_np or self.driver.min_np
            try:
                self.driver.rendezvous.handle_put(
                    f"{self._ns}capacity/want", str(want).encode())
            except Exception:  # pragma: no cover
                pass
        self._thread = threading.Thread(
            target=self._loop, name="elasticity-controller", daemon=True)
        self._thread.start()
        logger.info("elasticity controller started (interval %.0fs)",
                    self.interval)

    def _loop(self):
        while not self._stop.wait(self.interval):
            if self.driver.finished:
                return
            try:
                self.tick()
            except Exception as e:  # a bad tick must not kill the loop
                logger.warning("elasticity controller tick failed: %s", e)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
