"""`horovod_tpu.mxnet` — drop-in surface of `horovod.mxnet` for MXNet
users (ref: horovod/mxnet/__init__.py:38-164, horovod/mxnet/mpi_ops.py).

    import horovod_tpu.mxnet as hvd
    hvd.init()
    trainer = hvd.DistributedTrainer(params, opt)
    hvd.broadcast_parameters(model.collect_params(), root_rank=0)

NDArrays ride the same asynchronous name-negotiated engine as the JAX
eager path (numpy bridge); on TPU hardware the JAX path is the
performance surface — this adapter exists for capability parity and
CPU-cluster jobs, like the torch adapter.
"""
from __future__ import annotations

import types
import warnings

import mxnet as mx

from .functions import allgather_object, broadcast_object  # noqa: F401
from .mpi_ops import (  # noqa: F401
    allgather,
    allreduce,
    allreduce_,
    alltoall,
    broadcast,
    broadcast_,
    ccl_built,
    cross_rank,
    cross_size,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    rocm_built,
    shutdown,
    size,
)


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wraps an mx optimizer: allreduce(SUM) each grad in update(), with
    averaging folded into rescale_grad for performance
    (ref: horovod/mxnet/__init__.py:38-83)."""

    def __init__(self, optimizer, gradient_predivide_factor=1.0):
        self._optimizer = optimizer
        # Folding 1/size into rescale_grad is equivalent to averaging in
        # allreduce but cheaper (ref: __init__.py:44-47).
        self._optimizer.rescale_grad *= (gradient_predivide_factor / size())
        self._gradient_predivide_factor = gradient_predivide_factor

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if size() == 1:
            return
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                allreduce_(grad[i], average=False, name=str(index[i]),
                           priority=-i,
                           prescale_factor=1.0 / self._gradient_predivide_factor)
        else:
            allreduce_(grad, average=False, name=str(index),
                       prescale_factor=1.0 / self._gradient_predivide_factor)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


class DistributedTrainer(mx.gluon.Trainer):
    """gluon Trainer whose _allreduce_grads runs the engine's allreduce
    instead of kvstore push/pull (ref: horovod/mxnet/__init__.py:91-120)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 gradient_predivide_factor=1.0):
        if isinstance(optimizer, DistributedOptimizer):
            optimizer = optimizer._optimizer
            warnings.warn(
                "DistributedTrainer does not take DistributedOptimizer as "
                "its optimizer. We have unwrapped it for you."
            )
        super().__init__(params, optimizer,
                         optimizer_params=optimizer_params, kvstore=None)
        # _scale feeds rescale_grad in Trainer.step(); dividing by size
        # turns the summed allreduce into an average.
        self._scale *= (gradient_predivide_factor / size())
        self._gradient_predivide_factor = gradient_predivide_factor

    def _allreduce_grads(self):
        if size() == 1:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                allreduce_(param.list_grad()[0], average=False,
                           name=param.name, priority=-i,
                           prescale_factor=1.0 / self._gradient_predivide_factor)


def _append_broadcast_init(param, root_rank):
    """Inject a broadcast after deferred parameter initialization
    (ref: horovod/mxnet/__init__.py:121-127)."""
    init_impl = getattr(param, "_init_impl")

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank, name=self.name)

    return wrapped_init_impl


def broadcast_parameters(params, root_rank=0):
    """Broadcast a dict / gluon ParameterDict of parameters from
    root_rank (ref: horovod/mxnet/__init__.py:129-164)."""
    if size() == 1:
        return
    tensors = []
    names = []
    if isinstance(params, dict):
        names, tensors = zip(*sorted(params.items()))
    else:
        # gluon ParameterDict (or any mapping of name -> Parameter).
        deferred_error = getattr(
            mx.gluon.parameter, "DeferredInitializationError", Exception
        )
        for name, p in sorted(params.items()):
            try:
                tensors.append(p.data())
                names.append(name)
            except deferred_error:
                p._init_impl = types.MethodType(
                    _append_broadcast_init(p, root_rank), p
                )
    for tensor, name in zip(tensors, names):
        broadcast_(tensor, root_rank, name=str(name))
