"""Object helpers for the MXNet adapter.

(ref: horovod/mxnet/functions.py:22-97 broadcast_object/allgather_object
— pickle + broadcast/allgather of byte tensors; here delegated to the
framework-agnostic implementations in common.functions.)
"""
from __future__ import annotations

from ..common.functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
)
