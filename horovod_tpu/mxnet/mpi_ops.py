"""MXNet collective ops over the engine's numpy bridge.

(ref: horovod/mxnet/mpi_ops.py:51-306 — the reference pushes ops onto
MXNet's async engine with priorities; here NDArrays bridge through
numpy into the same asynchronous name-negotiated engine the JAX eager
path and the torch adapter use. MXNet's own async scheduler is fronted
by `wait_to_read()` before handoff, which plays the role of the
reference's dependency registration.)
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..common import basics as _basics
from ..common.basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    gloo_built,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..common.exceptions import HorovodInternalError
from ..common.types import ReduceOp


def mpi_threads_supported() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_enabled() -> bool:
    return True


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def _engine():
    eng = _basics.engine()
    if eng is None:
        raise HorovodInternalError(
            "horovod_tpu.mxnet collectives need process mode (hvdrun) or "
            "size()==1"
        )
    return eng


def _to_numpy(tensor) -> np.ndarray:
    tensor.wait_to_read()
    return tensor.asnumpy()


def _write_back(tensor, arr: np.ndarray):
    tensor[:] = arr.reshape(tensor.shape)
    return tensor


def _like(tensor, arr: np.ndarray):
    import mxnet as mx

    return mx.nd.array(arr, ctx=tensor.context, dtype=arr.dtype)


def _resolve_op(average: Optional[bool]) -> ReduceOp:
    return ReduceOp.AVERAGE if (average is None or average) else ReduceOp.SUM


def allreduce(tensor, average=True, name=None, priority=0,
              prescale_factor=1.0, postscale_factor=1.0):
    """(ref: mxnet/mpi_ops.py allreduce — returns a new NDArray.)"""
    rop = _resolve_op(average)
    arr = _to_numpy(tensor)
    if _basics.size() == 1:
        return _like(tensor, arr * prescale_factor * postscale_factor)
    out = _engine().synchronize(_engine().enqueue_allreduce(
        arr, name=name, op=rop,
        prescale=prescale_factor, postscale=postscale_factor,
    ))
    return _like(tensor, np.asarray(out))


def allreduce_(tensor, average=True, name=None, priority=0,
               prescale_factor=1.0, postscale_factor=1.0):
    """In-place allreduce (ref: mxnet/mpi_ops.py allreduce_)."""
    rop = _resolve_op(average)
    arr = _to_numpy(tensor)
    if _basics.size() == 1:
        return _write_back(tensor, arr * prescale_factor * postscale_factor)
    out = _engine().synchronize(_engine().enqueue_allreduce(
        arr, name=name, op=rop,
        prescale=prescale_factor, postscale=postscale_factor,
    ))
    return _write_back(tensor, np.asarray(out))


def allgather(tensor, name=None, priority=0):
    """Concatenate along dim 0 across ranks (variable first dim OK)."""
    arr = _to_numpy(tensor)
    if _basics.size() == 1:
        return _like(tensor, arr)
    out = _engine().synchronize(_engine().enqueue_allgather(arr, name=name))
    return _like(tensor, np.asarray(out))


def broadcast(tensor, root_rank, name=None, priority=0):
    arr = _to_numpy(tensor)
    if _basics.size() == 1:
        return _like(tensor, arr)
    out = _engine().synchronize(
        _engine().enqueue_broadcast(arr, root_rank, name=name)
    )
    return _like(tensor, np.asarray(out))


def broadcast_(tensor, root_rank, name=None, priority=0):
    arr = _to_numpy(tensor)
    if _basics.size() == 1:
        return tensor
    out = _engine().synchronize(
        _engine().enqueue_broadcast(arr, root_rank, name=name)
    )
    return _write_back(tensor, np.asarray(out))


def alltoall(tensor, splits=None, name=None, priority=0):
    arr = _to_numpy(tensor)
    if _basics.size() == 1:
        return _like(tensor, arr)
    out, _recv = _engine().synchronize(_engine().enqueue_alltoall(
        arr, list(splits.asnumpy()) if hasattr(splits, "asnumpy")
        else (list(splits) if splits is not None else None),
        name=name,
    ))
    return _like(tensor, np.asarray(out))
