"""Keras callbacks (ref: horovod/_keras/callbacks.py:22-192,
horovod/callbacks surface horovod/keras/callbacks.py).

Real `keras.callbacks.Callback` subclasses, usable directly in
`model.fit(callbacks=[...])`. The JAX-loop ports of the same callbacks
live in `horovod_tpu.callbacks` for users running custom JAX loops.
"""
from __future__ import annotations

from typing import Optional

import keras
import numpy as np


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast model + optimizer state from root at train begin
    (ref: _keras/callbacks.py:22-46)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        # End of the first batch, not the beginning: lazily-built models
        # have no variables until the first forward pass has run
        # (ref: _keras/callbacks.py broadcasts on_batch_end for this).
        if self.broadcast_done:
            return
        from ..tensorflow import broadcast_variables

        broadcast_variables(self.model.variables, root_rank=self.root_rank)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None:
            v = getattr(opt, "variables", [])
            opt_vars = list(v() if callable(v) else v)
            if opt_vars:
                broadcast_variables(opt_vars, root_rank=self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over ranks before other callbacks (e.g.
    checkpointers) read them (ref: _keras/callbacks.py:48-88)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None:
            return
        from ..tensorflow import allreduce
        from ..common.types import ReduceOp

        for k in sorted(logs.keys()):
            v = logs[k]
            if isinstance(v, (int, float, np.floating, np.integer)):
                logs[k] = float(np.asarray(allreduce(
                    np.asarray(v, np.float64), op=ReduceOp.AVERAGE,
                    name=f"metric.{epoch}.{k}",
                )))


class MetricsCallback(keras.callbacks.Callback):
    """One-line telemetry summary (step time, allreduce MB/s, cache
    hit %) every `interval` batches, from rank 0 only — the Keras
    spelling of horovod_tpu.callbacks.MetricsCallback
    (docs/metrics.md)."""

    def __init__(self, interval: int = 100, log_fn=None,
                 root_only: bool = True, registry=None):
        super().__init__()
        from ..common import telemetry

        self._logger = telemetry.StepSummaryLogger(
            interval, log_fn, root_only, registry)

    def on_batch_end(self, batch, logs=None):
        self._logger.step()


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Schedule LR as multiplier(epoch) × initial
    (ref: _keras/callbacks.py:90-145)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True, steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if not callable(multiplier):
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _set_lr(self, epoch):
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return
        if epoch < self.start_epoch:
            return
        lr = self.initial_lr * self.multiplier(epoch)
        opt = self.model.optimizer
        try:
            opt.learning_rate.assign(lr)
        except AttributeError:
            opt.learning_rate = lr

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        # Smooth schedules without a known steps_per_epoch still update
        # once per epoch — never silently skip (the reference derives
        # steps from Keras params; ref: _keras/callbacks.py:117-136).
        if self.staircase or not self.steps_per_epoch:
            self._set_lr(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase and self.steps_per_epoch:
            self._set_lr(self.current_epoch + batch / self.steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            try:
                logs["lr"] = float(
                    np.asarray(self.model.optimizer.learning_rate)
                )
            except Exception:
                pass


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup from initial_lr to initial_lr×size over
    warmup_epochs (ref: _keras/callbacks.py:147-192)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        from ..common.basics import size

        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        n = size()

        def multiplier(epoch):
            if epoch >= warmup_epochs:
                return n
            return 1.0 + (n - 1.0) * epoch / max(warmup_epochs, 1)

        super().__init__(initial_lr, multiplier, start_epoch=0,
                         end_epoch=None, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if self.verbose and epoch == self.warmup_epochs:
            print(f"Epoch {epoch}: finished gradual learning rate warmup.")
