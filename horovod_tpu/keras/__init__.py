"""`horovod_tpu.keras` — drop-in surface of `horovod.keras`
(ref: horovod/keras/__init__.py, horovod/_keras/__init__.py).

    import horovod_tpu.keras as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.01 * hvd.size()))
    model.compile(optimizer=opt, ...)
    callbacks = [hvd.callbacks.BroadcastGlobalVariablesCallback(0)]

Targets Keras 3 (the version shipping with TF 2.16+): the wrapper
subclasses the inner optimizer's class and intercepts
`apply_gradients`/`apply`, the single funnel Keras 3 routes all updates
through — the same interception point as the reference's dynamic
subclass overriding get_gradients/_aggregate_gradients
(ref: horovod/_keras/__init__.py:27-143).
"""
from __future__ import annotations

from typing import Optional

from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    metrics,
    mpi_built,
    gloo_built,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..common.types import Adasum, Average, ReduceOp, Sum  # noqa: F401
from ..tensorflow import (  # noqa: F401
    allgather,
    allgather_object,
    allreduce,
    broadcast,
    broadcast_object,
    broadcast_variables,
    join,
    barrier,
)
from ..tensorflow.compression import Compression  # noqa: F401
from . import callbacks  # noqa: F401
from .elastic import KerasState  # noqa: F401


def DistributedOptimizer(
    optimizer,
    name: Optional[str] = None,
    device_dense: str = "",
    device_sparse: str = "",
    compression=None,
    sparse_as_dense: bool = False,
    gradient_predivide_factor: float = 1.0,
    op: ReduceOp = ReduceOp.AVERAGE,
    backward_passes_per_step: int = 1,
):
    """Wrap a Keras optimizer so gradients are allreduced across ranks
    before being applied (ref: horovod/keras/__init__.py:34-82,
    horovod/_keras/__init__.py:27-143).

    `backward_passes_per_step > 1` accumulates locally and communicates
    + applies on every k-th call, the reference's local-gradient-
    aggregation semantics (ref: _keras/__init__.py:62-116).
    """
    from ..tensorflow import _make_allreduce_grads_fn

    cls = type(optimizer)
    k = int(backward_passes_per_step)

    if op == ReduceOp.ADASUM and size() > 1:
        if gradient_predivide_factor != 1.0:
            # (ref: horovod/torch/optimizer.py:431-435 — predivide is
            # Average-only; silently ignoring it would change the
            # effective update.)
            raise ValueError(
                "gradient_predivide_factor is not supported with "
                "op=Adasum"
            )
        return _make_adasum_optimizer(
            optimizer, name, device_dense, device_sparse,
            compression or Compression.none, sparse_as_dense, k,
        )

    allreduce_grads = _make_allreduce_grads_fn(
        name or f"Distributed{cls.__name__}", device_dense, device_sparse,
        compression or Compression.none, sparse_as_dense, op,
        gradient_predivide_factor,
    )

    class _DistributedOptimizer(cls):
        _hvd_wrapped = True

        def __init__(self):
            # Adopt the wrapped instance's state wholesale: Keras 3
            # optimizers are plain python objects with tracked
            # variables; re-pointing __dict__ makes this instance an
            # alias of the original with overridden apply methods.
            object.__setattr__(self, "__dict__", optimizer.__dict__)
            object.__setattr__(self, "_hvd_acc", None)
            object.__setattr__(self, "_hvd_count", 0)

        # Keras 3 funnels model.fit / apply_gradients through apply().
        def apply(self, grads, trainable_variables=None):
            import tensorflow as tf

            grads = list(grads)
            if k <= 1:
                reduced = allreduce_grads(grads)
                return cls.apply(self, reduced, trainable_variables)

            # Local accumulation (eager path; the reference's
            # LocalGradientAggregationHelper equivalent).
            if self._hvd_acc is None:
                self._hvd_acc = [
                    tf.Variable(tf.zeros_like(g), trainable=False)
                    for g in grads
                ]
            for acc, g in zip(self._hvd_acc, grads):
                acc.assign_add(g)
            self._hvd_count += 1
            if self._hvd_count % k:
                return None
            # Accumulated passes are NOT rescaled by 1/k: the effective
            # batch grows, matching the reference default
            # (average_aggregated_gradients=False) and the torch adapter.
            reduced = allreduce_grads([a.value() for a in self._hvd_acc])
            for a in self._hvd_acc:
                a.assign(tf.zeros_like(a))
            return cls.apply(self, reduced, trainable_variables)

        def apply_gradients(self, grads_and_vars, **kwargs):
            grads, tvars = zip(*list(grads_and_vars))
            return self.apply(list(grads), list(tvars))

        # Config round-trip: get_config is the inner optimizer's config
        # (the wrapper adds no hyperparameters), and from_config
        # rebuilds the inner optimizer then re-wraps it, so
        # keras.models.clone_model / serialize→deserialize paths that
        # call type(opt).from_config(opt.get_config()) yield a working
        # distributed optimizer without custom_objects
        # (ref: horovod/keras/__init__.py:137-152 — the reference keeps
        # a registry of wrapped classes for the same purpose; file-based
        # load still goes through load_model(), which maps the
        # Distributed<X> class name back to a wrapper).
        def get_config(self):
            return cls.get_config(self)

        @classmethod
        def from_config(cls_, config, custom_objects=None):
            try:
                base = cls.from_config(config, custom_objects)
            except TypeError:  # base from_config without custom_objects
                base = cls.from_config(config)
            return DistributedOptimizer(
                base, name=name, device_dense=device_dense,
                device_sparse=device_sparse, compression=compression,
                sparse_as_dense=sparse_as_dense,
                gradient_predivide_factor=gradient_predivide_factor,
                op=op, backward_passes_per_step=backward_passes_per_step,
            )

    _DistributedOptimizer.__name__ = f"Distributed{cls.__name__}"
    return _DistributedOptimizer()


def _make_adasum_optimizer(optimizer, name, device_dense, device_sparse,
                           compression, sparse_as_dense, k):
    """Delta-model Adasum wrapper (ref: horovod/tensorflow/__init__.py:
    334-428 _DistributedAdasumOptimizer).

    `op=Adasum` does NOT Adasum-allreduce gradients. Per variable:

        start = var            (captured on the first apply)
        local step             (the wrapped optimizer's own update)
        every k-th apply:
            delta  = var - start
            delta  = adasum(delta)   (compressed, grouped VHDD combine)
            start += delta
            var    = start

    Between communication steps the local optimizer keeps stepping on
    `var` (the reference's `_is_comm_step` schedule, :356,383-386) —
    unlike the gradient wrapper, which accumulates grads and applies
    once per boundary. The k-schedule lives IN-GRAPH — a tf.Variable
    iteration counter gating the combine through `tf.cond` — so it
    survives a traced `model.fit` exactly as the reference bakes
    `_is_comm_step` into the graph.
    """
    from ..tensorflow import _make_allreduce_grads_fn

    cls = type(optimizer)
    allreduce_deltas = _make_allreduce_grads_fn(
        name or f"DistributedDelta{cls.__name__}", device_dense,
        device_sparse, compression, sparse_as_dense, ReduceOp.ADASUM, 1.0,
    )

    class _DistributedAdasumOptimizer(cls):
        _hvd_wrapped = True

        def __init__(self):
            object.__setattr__(self, "__dict__", optimizer.__dict__)
            object.__setattr__(self, "_hvd_start", None)
            object.__setattr__(self, "_hvd_iter", None)

        def _hvd_combine(self, tvars):
            import tensorflow as tf

            deltas = [
                tf.convert_to_tensor(v) - s
                for v, s in zip(tvars, self._hvd_start)
            ]
            combined = allreduce_deltas(deltas)
            for v, s, d in zip(tvars, self._hvd_start, combined):
                s.assign_add(d)
                v.assign(s)
            return tf.constant(True)

        def apply(self, grads, trainable_variables=None):
            import tensorflow as tf

            grads = list(grads)
            tvars = trainable_variables
            if tvars is None:
                tvars = getattr(self, "_trainable_variables", None)
            if tvars is None:
                raise ValueError(
                    "Adasum DistributedOptimizer needs the trainable "
                    "variables: pass them to apply()/apply_gradients() "
                    "or build the optimizer first"
                )
            tvars = list(tvars)
            # First step: start <- var (ref: __init__.py:361-364). The
            # iteration counter is a tf.Variable so the k-schedule is
            # part of the graph, not Python trace-time state.
            if self._hvd_start is None:
                self._hvd_start = [
                    tf.Variable(tf.convert_to_tensor(v), trainable=False)
                    for v in tvars
                ]
                self._hvd_iter = tf.Variable(
                    0, dtype=tf.int64, trainable=False
                )
                # Adasum delta-baseline trap (docs/adasum.md): each rank
                # combines (var - start), so ranks reaching this first
                # apply with non-identical weights (e.g. a broadcast
                # deferred to a batch-0 callback that hasn't fired)
                # would anchor divergent baselines and silently drift
                # forever. Broadcasting the baseline itself from rank 0
                # makes the anchor rank-identical by construction. The
                # in-graph iter==0 gate keeps it ONE broadcast even
                # under tf.function, where this creation block is baked
                # into the first concrete trace and would otherwise
                # re-broadcast every step of that trace. (No init_scope:
                # the variables' lifted initializers haven't run at
                # trace time, so an eager read here would see
                # uninitialized storage. All ranks share the counter
                # trajectory, so the branches stay collectively
                # aligned, like the k-schedule below.)
                def _sync_baseline():
                    broadcast_variables(self._hvd_start, root_rank=0)
                    return tf.constant(True)

                tf.cond(
                    tf.equal(self._hvd_iter, 0),
                    _sync_baseline,
                    lambda: tf.constant(False),
                )
            result = cls.apply(self, grads, trainable_variables)
            it = self._hvd_iter.assign_add(1)
            if k <= 1:
                self._hvd_combine(tvars)
                return result
            # In-graph comm-step schedule (ref: `_is_comm_step`,
            # horovod/tensorflow/__init__.py:356,383-386): local step
            # every batch, delta-combine every k-th. All ranks share the
            # counter trajectory, so the branches stay aligned.
            tf.cond(
                tf.equal(it % k, 0),
                lambda: self._hvd_combine(tvars),
                lambda: tf.constant(False),
            )
            return result

        def apply_gradients(self, grads_and_vars, **kwargs):
            grads, tvars = zip(*list(grads_and_vars))
            return self.apply(list(grads), list(tvars))

        def get_config(self):
            return cls.get_config(self)

        @classmethod
        def from_config(cls_, config, custom_objects=None):
            try:
                base = cls.from_config(config, custom_objects)
            except TypeError:
                base = cls.from_config(config)
            return DistributedOptimizer(
                base, name=name, device_dense=device_dense,
                device_sparse=device_sparse, compression=compression,
                sparse_as_dense=sparse_as_dense, op=ReduceOp.ADASUM,
                backward_passes_per_step=k,
            )

    _DistributedAdasumOptimizer.__name__ = f"DistributedDelta{cls.__name__}"
    return _DistributedAdasumOptimizer()


def broadcast_global_variables(model_or_variables, root_rank: int = 0):
    """Broadcast a model's (or variable list's) values from root
    (ref: horovod/keras/__init__.py:84-93)."""
    variables = getattr(model_or_variables, "variables",
                        model_or_variables)
    broadcast_variables(variables, root_rank=root_rank)


def _wrapped_optimizer_loader(base_cls, compression):
    """Deserialization shim: models saved with a Distributed<X> optimizer
    reference a class that only ever existed dynamically; this recreates
    base_cls from config and re-wraps it
    (ref: horovod/keras/__init__.py:137-152 horovod_objects)."""

    class _Loader:
        @classmethod
        def from_config(cls, config, custom_objects=None):
            return DistributedOptimizer(
                base_cls.from_config(config), compression=compression
            )

    _Loader.__name__ = f"Distributed{base_cls.__name__}"
    return _Loader


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Load a Keras model, wrapping its optimizer in DistributedOptimizer
    (ref: horovod/keras/__init__.py:127-158)."""
    import keras

    cobj = dict(custom_objects or {})
    base_classes = list(custom_optimizers or [])
    for name in dir(keras.optimizers):
        c = getattr(keras.optimizers, name)
        if isinstance(c, type) and issubclass(c, keras.optimizers.Optimizer):
            base_classes.append(c)
    for c in base_classes:
        cobj.setdefault(
            f"Distributed{c.__name__}",
            _wrapped_optimizer_loader(c, compression),
        )

    model = keras.models.load_model(filepath, custom_objects=cobj)
    opt = getattr(model, "optimizer", None)
    if opt is not None and not getattr(opt, "_hvd_wrapped", False):
        # The wrapper aliases the loaded optimizer's state (shared
        # __dict__), so swapping the attribute in place keeps the
        # compiled loss/metrics intact — no recompile needed.
        model.optimizer = DistributedOptimizer(opt, compression=compression)
    return model
