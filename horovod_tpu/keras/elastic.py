"""Elastic Keras state + callbacks (ref: horovod/keras/elastic.py:20-83,
horovod/_keras/elastic.py:24-59)."""
import keras

from ..tensorflow.elastic import TensorFlowKerasState as KerasState  # noqa: F401


class CommitStateCallback(keras.callbacks.Callback):
    """Commit elastic state every `batches_per_commit` batches
    (ref: horovod/_keras/elastic.py:24-40)."""

    def __init__(self, state, batches_per_commit: int = 1):
        super().__init__()
        self.state = state
        self.batches_per_commit = batches_per_commit
        self._counter = 0

    def on_batch_end(self, batch, logs=None):
        self._counter += 1
        if self._counter % self.batches_per_commit == 0:
            self.state.commit()


class UpdateBatchStateCallback(keras.callbacks.Callback):
    """Track batch progress in elastic state
    (ref: horovod/_keras/elastic.py:43-59)."""

    def __init__(self, state):
        super().__init__()
        self.state = state

    def on_batch_end(self, batch, logs=None):
        self.state.batch = batch

    def on_epoch_end(self, epoch, logs=None):
        self.state.batch = 0


class UpdateEpochStateCallback(keras.callbacks.Callback):
    """Track epoch progress in elastic state."""

    def __init__(self, state):
        super().__init__()
        self.state = state

    def on_epoch_end(self, epoch, logs=None):
        self.state.epoch = epoch
