"""Model registry: name → (flax module, synthetic-batch factory).

Mirrors how the reference's benchmark scripts look models up by name
(`examples/pytorch_synthetic_benchmark.py --model resnet50` resolves
through `torchvision.models.__dict__`). Synthetic batches match the
benchmark data shapes (224x224x3 images; token ids for LMs).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import numpy as np

from .mnist import MnistCNN, MnistMLP
from .resnet import RESNET_CONFIGS
from .transformer import (
    BERT_CONFIGS,
    GPT2_CONFIGS,
    TransformerConfig,
    TransformerEncoder,
    TransformerLM,
)
from .vit import VIT_CONFIGS, ViT


@dataclasses.dataclass
class ModelSpec:
    name: str
    make_model: Callable[..., Any]
    make_batch: Callable[[int], Any]   # batch_size -> example inputs tuple
    kind: str                          # "image" | "lm" | "encoder"


def _image_batch(hw: int, channels: int = 3):
    def make(batch_size: int, seed: int = 0):
        rng = np.random.RandomState(seed)
        return (rng.rand(batch_size, hw, hw, channels).astype(np.float32),)

    return make


def _token_batch(seq_len: int, vocab: int):
    def make(batch_size: int, seed: int = 0, seq_len: int = seq_len):
        rng = np.random.RandomState(seed)
        return (rng.randint(0, vocab, size=(batch_size, seq_len),
                            dtype=np.int32),)

    return make


def _registry() -> Dict[str, ModelSpec]:
    reg: Dict[str, ModelSpec] = {}
    reg["mnist-mlp"] = ModelSpec("mnist-mlp", MnistMLP, _image_batch(28, 1),
                                 "image")
    reg["mnist-cnn"] = ModelSpec("mnist-cnn", MnistCNN, _image_batch(28, 1),
                                 "image")
    for name, ctor in RESNET_CONFIGS.items():
        reg[name] = ModelSpec(name, ctor, _image_batch(224), "image")
    for name, cfg in GPT2_CONFIGS.items():
        reg[name] = ModelSpec(
            name,
            (lambda c: (lambda **kw: TransformerLM(
                dataclasses.replace(c, **kw) if kw else c)))(cfg),
            _token_batch(min(cfg.max_len, 512), cfg.vocab_size),
            "lm",
        )
    for name, cfg in BERT_CONFIGS.items():
        reg[name] = ModelSpec(
            name,
            (lambda c: (lambda **kw: TransformerEncoder(
                dataclasses.replace(c, **kw) if kw else c)))(cfg),
            _token_batch(min(cfg.max_len, 128), cfg.vocab_size),
            "encoder",
        )
    for name, cfg in VIT_CONFIGS.items():
        reg[name] = ModelSpec(
            name,
            (lambda c: (lambda **kw: ViT(
                dataclasses.replace(c, **kw) if kw else c)))(cfg),
            _image_batch(cfg.image_size), "image",
        )
    return reg


REGISTRY = _registry()


def get_model(name: str) -> ModelSpec:
    if name not in REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(REGISTRY)}"
        )
    return REGISTRY[name]


def list_models():
    return sorted(REGISTRY)
