"""Vision Transformer — parity target: reference north-star "ViT-L/16
ImageNet DP" (BASELINE.json). Reuses the transformer encoder stack; the
patch embedding is a strided conv (one big MXU matmul per image)."""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .transformer import (
    TransformerConfig,
    TransformerStack,
    functools_partial_ln,
    default_kernel_init,
)


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    scan_layers: bool = False

    def transformer(self) -> TransformerConfig:
        n_patches = (self.image_size // self.patch_size) ** 2
        return TransformerConfig(
            vocab_size=self.num_classes,
            d_model=self.d_model,
            n_heads=self.n_heads,
            n_layers=self.n_layers,
            d_ff=self.d_ff,
            max_len=n_patches + 1,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            causal=False,
            remat=self.remat,
            scan_layers=self.scan_layers,
        )


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        cfg = self.cfg
        tcfg = cfg.transformer()
        p = cfg.patch_size
        x = nn.Conv(
            cfg.d_model, (p, p), strides=(p, p), padding="VALID",
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                default_kernel_init, (None, None, None, "embed")
            ),
            name="patch_embed",
        )(images.astype(cfg.dtype))
        B, H, W, D = x.shape
        x = x.reshape(B, H * W, D)
        cls = self.param(
            "cls",
            nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                         (None, None, "embed")),
            (1, 1, D), cfg.param_dtype,
        )
        x = jnp.concatenate([jnp.tile(cls.astype(cfg.dtype), (B, 1, 1)), x],
                            axis=1)
        pos = self.param(
            "pos_embedding",
            nn.with_logical_partitioning(default_kernel_init, (None, "embed")),
            (H * W + 1, D), cfg.param_dtype,
        )
        x = x + pos[None].astype(cfg.dtype)
        x = TransformerStack(tcfg, name="stack")(x, None, deterministic)
        x = functools_partial_ln(tcfg)(name="ln_f")(x)
        x = x[:, 0]  # CLS token
        logits = nn.Dense(
            cfg.num_classes, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(default_kernel_init,
                                                     ("embed", "vocab")),
            name="head",
        )(x)
        return logits.astype(jnp.float32)


VIT_CONFIGS = {
    "vit-tiny": ViTConfig(image_size=32, patch_size=4, num_classes=10,
                          d_model=64, n_heads=4, n_layers=2, d_ff=256),
    "vit-s16": ViTConfig(d_model=384, n_heads=6, n_layers=12, d_ff=1536),
    "vit-b16": ViTConfig(d_model=768, n_heads=12, n_layers=12, d_ff=3072),
    "vit-l16": ViTConfig(d_model=1024, n_heads=16, n_layers=24, d_ff=4096),
}
