"""ResNet v1.5 family — the reference's headline benchmark workload.

Parity target: `examples/pytorch_synthetic_benchmark.py` /
`examples/tensorflow2_synthetic_benchmark.py` (torchvision/keras
`applications.ResNet50`) and the published scaling numbers for
ResNet-101 (docs/benchmarks.rst:11-43). This is a data-parallel
workload: convolutions are the MXU hot path, so compute in bfloat16 with
fp32 params and fp32 BatchNorm statistics; shapes are static so XLA can
tile every conv onto the systolic array.

v1.5 detail (matches torchvision): the stride-2 downsampling in
bottleneck blocks happens in the 3x3 conv, not the first 1x1.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class FusedBNReluConv1x1(nn.Module):
    """BN-apply + ReLU + 1x1-conv in ONE pass over the activation via
    the Pallas kernel (`ops/fused_bn_conv.py` — 1.36x the XLA unfused
    chain on the stage-2 shape, docs/kernels.md). Owns the same
    BN state flax.BatchNorm would (batch stats in train, running-stat
    EMA) plus the conv kernel, so it is a drop-in for the
    [norm → act → conv1x1] tail of a bottleneck block."""

    features: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = True):
        from ..ops.fused_bn_conv import bn_relu_conv1x1

        cin = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones_init(), (cin,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (cin,),
                          jnp.float32)
        kernel = self.param(
            "kernel",
            nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
            (cin, self.features), self.param_dtype,
        )
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((cin,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((cin,), jnp.float32))
        x2d = x.reshape(-1, cin)
        if train:
            xf = x2d.astype(jnp.float32)
            mu = jnp.mean(xf, axis=0)
            # E[x^2]-E[x]^2 can round below 0 for near-constant
            # channels of large magnitude; clamp so rsqrt(var+eps)
            # in the kernel can't go NaN.
            var = jnp.maximum(
                jnp.mean(jnp.square(xf), axis=0) - jnp.square(mu), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mu
                ra_var.value = m * ra_var.value + (1 - m) * var
        else:
            mu, var = ra_mean.value, ra_var.value
        # The kernel requires M % block_m == 0 (block_m = min(512, M)):
        # pad rows with zeros and slice them back off — padded rows'
        # outputs are garbage-but-finite and never read (batch stats
        # were computed on the unpadded rows above; the kernel's own
        # epilogue stats are discarded here).
        m = x2d.shape[0]
        pad = (-m) % 512 if m > 512 else 0
        if pad:
            x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
        y2d, _, _ = bn_relu_conv1x1(
            x2d, mu, var, scale, bias, kernel.astype(self.dtype),
            self.epsilon,
        )
        return y2d[:m].reshape(*x.shape[:-1], self.features)


class BottleneckResNetBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1 bottleneck (ResNet-50/101/152).

    `fuse_bn_conv1x1=True` routes the [norm → act → 1x1-conv] tail
    through the Pallas fused kernel (see FusedBNReluConv1x1) — the
    flag exists to measure that kernel's end-to-end contribution
    (bench.py measures it by default for resnet50; `--no-fused-bn`
    skips)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)
    fuse_bn_conv1x1: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        if self.fuse_bn_conv1x1:
            # The ResNet trunk encodes train/eval in the norm partial;
            # mirror it so the fused site keeps BatchNorm semantics.
            train = not self.norm.keywords.get("use_running_average",
                                               False)
            y = FusedBNReluConv1x1(
                self.filters * 4, dtype=y.dtype, name="fused_bn_conv3",
            )(y, train=train)
            y = self.norm(scale_init=nn.initializers.zeros_init())(y)
            if residual.shape != y.shape:
                residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                     name="conv_proj")(residual)
                residual = self.norm(name="norm_proj")(residual)
            return self.act(residual + y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init final BN scale: residual branch starts as identity
        # (same trick the reference benchmarks rely on for large-batch
        # convergence; goyal et al. linear-scaling recipe).
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    act: Callable = nn.relu
    # Stages (0-based) whose bottleneck 1x1 tails run the Pallas fused
    # BN+ReLU+conv kernel — measurement flag, bottleneck blocks only
    # (see FusedBNReluConv1x1; docs/kernels.md for which shapes win).
    fuse_bn_conv_stages: Sequence[int] = ()

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out",
                                                         "normal"),
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                kw = {}
                if (i in self.fuse_bn_conv_stages
                        and self.block_cls is BottleneckResNetBlock):
                    kw["fuse_bn_conv1x1"] = True
                x = self.block_cls(
                    self.num_filters * 2**i,
                    conv=conv, norm=norm, act=self.act, strides=strides,
                    **kw,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckResNetBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckResNetBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckResNetBlock)

RESNET_CONFIGS = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
}
