"""ResNet v1.5 family — the reference's headline benchmark workload.

Parity target: `examples/pytorch_synthetic_benchmark.py` /
`examples/tensorflow2_synthetic_benchmark.py` (torchvision/keras
`applications.ResNet50`) and the published scaling numbers for
ResNet-101 (docs/benchmarks.rst:11-43). This is a data-parallel
workload: convolutions are the MXU hot path, so compute in bfloat16 with
fp32 params and fp32 BatchNorm statistics; shapes are static so XLA can
tile every conv onto the systolic array.

v1.5 detail (matches torchvision): the stride-2 downsampling in
bottleneck blocks happens in the 3x3 conv, not the first 1x1.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1 bottleneck (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init final BN scale: residual branch starts as identity
        # (same trick the reference benchmarks rely on for large-batch
        # convergence; goyal et al. linear-scaling recipe).
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out",
                                                         "normal"),
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    conv=conv, norm=norm, act=self.act, strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckResNetBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckResNetBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckResNetBlock)

RESNET_CONFIGS = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
}
