"""Transformer model family (GPT-2 causal LM, BERT encoder, ViT) —
TPU-first flax implementation shared by the model zoo.

The reference frames these as *workloads* (BASELINE.json north-star
configs: BERT-base DistributedGradientTape, GPT-2 1.3B + Adasum; its own
examples are torch/TF scripts, e.g. examples/pytorch_synthetic_benchmark.py).
Here they are first-class models designed for the MXU and for mesh
parallelism:

* bfloat16 activations / fp32 params (MXU-native mixed precision);
* every parameter is annotated with **logical axes** via
  `nn.with_logical_partitioning`; `parallel/sharding.py` maps logical
  axes → mesh axes (tp/ep/pp/...) so one model definition serves 1 chip
  or a v5p-128 without edits;
* activations carry `nn.with_logical_constraint` hints on (batch,
  sequence, embed) so dp/sp sharding propagates through the graph;
* static shapes everywhere; per-layer `nn.remat` option to trade FLOPs
  for HBM; optional `nn.scan` over layers for O(1) compile scaling;
* optional Mixture-of-Experts FFN (Switch-style top-1 routing with
  static capacity) whose expert dim is a logical axis → expert
  parallelism is just a sharding rule.

Logical axis vocabulary (mapped in parallel/sharding.py):
    "batch", "seq", "embed", "mlp", "heads", "kv", "vocab",
    "expert", "expert_mlp", "layers", "stage"
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compat import get_abstract_mesh as _get_abstract_mesh

Dtype = Any

default_kernel_init = nn.initializers.normal(stddev=0.02)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Hyperparameters for the transformer family."""

    vocab_size: int = 50257
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_len: int = 1024
    dropout_rate: float = 0.0
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    causal: bool = True
    # MoE: every `moe_every`-th block uses a Switch FFN with n_experts.
    n_experts: int = 0
    moe_every: int = 2
    capacity_factor: float = 1.25
    # Engineering knobs.
    remat: bool = False
    scan_layers: bool = False
    logits_via_embedding: bool = False
    # Output logits dtype. f32 is the DEFAULT: model.apply logits are a
    # public surface (sampling, logprob extraction, custom losses), and
    # silently narrowing them costs external consumers precision
    # (ADVICE r14). The measured bench/train paths OPT INTO bf16
    # explicitly (bench.py, examples/jax_gpt2_train.py): the (B, S, V)
    # logits tensor is the largest activation in the model (1.65 GB in
    # f32 at the GPT-2 bench shape) and every loss in this repo upcasts
    # to f32 *inside* its softmax reduction (parallel/train.py
    # softmax_xent), so emitting bf16 there saves the lm-head region's
    # HBM traffic — measured 6.0 ms of a 98 ms step on v5e
    # (docs/benchmarks.md, r5) — without changing the loss numerics.
    logits_dtype: Dtype = jnp.float32
    # Learned (gpt2/bert/vit) vs fixed sinusoidal positions.
    learned_pos: bool = True
    # Attention implementation: "dense", or the sequence-parallel kernels
    # "ring" (blockwise ppermute) / "ulysses" (all-to-all head exchange).
    # The sp kernels require an ambient mesh (jax.sharding.set_mesh /
    # make_train_step) containing `sp_axis`; they fall back to dense when
    # the axis is absent or trivial.
    attn_impl: str = "dense"
    sp_axis: str = "sp"
    # With attn_impl="ulysses": run the per-head-group attention through
    # the Pallas flash kernel instead of XLA dense (composes sequence
    # parallelism with the fused kernel).
    sp_use_flash: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _dense(features, cfg: TransformerConfig, name: str, logical_axes,
           use_bias: bool = True):
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(default_kernel_init, logical_axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (logical_axes[-1],)
        ),
        name=name,
    )


def _dense_attention_masked(cfg: TransformerConfig, q, k, v, mask):
    Hd = q.shape[-1]
    S = q.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(Hd)
    scores = scores.astype(jnp.float32)
    valid = None
    if cfg.causal:
        valid = jnp.tril(jnp.ones((S, S), dtype=bool))[None, None]
    if mask is not None:
        # mask: (B, S) 1 = attend, 0 = pad.
        km = mask[:, None, None, :].astype(bool)
        valid = km if valid is None else jnp.logical_and(valid, km)
    if valid is not None:
        scores = jnp.where(valid, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if valid is not None:
        # Fully-masked query rows yield zeros, not a uniform average of
        # every value — matching the sp kernels' convention
        # (parallel/ring.py _flash_block_update).
        probs = jnp.where(valid, probs, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(cfg.dtype), v)


def _attention_dispatch(cfg: TransformerConfig, q, k, v, mask):
    """Choose dense vs flash (Pallas) vs sequence-parallel attention.
    The sp kernels run in a nested shard_map that manualizes only
    `cfg.sp_axis`; batch/head sharding stays under GSPMD."""
    if cfg.attn_impl == "flash":
        # Fused Pallas kernel (ops/flash_attention.py): compiled on TPU,
        # interpreter elsewhere. Not combined with sp sharding — for
        # sequence parallelism use ring/ulysses. Under a GSPMD mesh the
        # opaque pallas_call would otherwise force full replication
        # (GSPMD can't partition through it), so batch/head axes are
        # manualized with shard_map; attention is independent per
        # (batch, head), so no collectives are needed inside.
        from ..ops.flash_attention import flash_attention

        am = _get_abstract_mesh()
        manual = [
            ax for ax in ("dp", "tp") if am is not None
            and ax in am.axis_names and am.shape[ax] > 1
        ]
        if not manual:
            return flash_attention(q, k, v, mask, causal=cfg.causal).astype(
                cfg.dtype)
        from jax.sharding import PartitionSpec as P

        from ..utils.compat import shard_map

        dp = "dp" if "dp" in manual else None
        tp = "tp" if "tp" in manual else None
        qkv_spec = P(dp, None, tp, None)   # (B, S, H, D)
        mask_spec = P(dp, None)            # (B, S)

        if mask is None:
            fn = shard_map(
                lambda q, k, v: flash_attention(q, k, v,
                                                causal=cfg.causal),
                mesh=am, in_specs=(qkv_spec,) * 3, out_specs=qkv_spec,
                axis_names=set(manual),
            )
            return fn(q, k, v).astype(cfg.dtype)
        fn = shard_map(
            lambda q, k, v, m: flash_attention(q, k, v, m,
                                               causal=cfg.causal),
            mesh=am, in_specs=(qkv_spec,) * 3 + (mask_spec,),
            out_specs=qkv_spec, axis_names=set(manual),
        )
        return fn(q, k, v, mask).astype(cfg.dtype)
    if cfg.attn_impl not in ("ring", "ulysses"):
        return _dense_attention_masked(cfg, q, k, v, mask)
    am = _get_abstract_mesh()
    if am is None or cfg.sp_axis not in am.axis_names \
            or am.shape[cfg.sp_axis] == 1:
        return _dense_attention_masked(cfg, q, k, v, mask)
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring import ring_attention
    from ..parallel.ulysses import ulysses_attention
    from ..utils.compat import shard_map

    if cfg.attn_impl == "ring":
        impl = ring_attention
    else:
        impl = functools.partial(ulysses_attention,
                                 use_flash=cfg.sp_use_flash)
    manual = {cfg.sp_axis}
    dp = tp = None
    if cfg.attn_impl != "ring" and cfg.sp_use_flash:
        # The flash pallas_call is opaque to GSPMD: batch/head axes must
        # be manualized too, or every dp/tp rank replicates the full
        # attention (same reason as the attn_impl="flash" branch above).
        dp = "dp" if "dp" in am.axis_names and am.shape["dp"] > 1 else None
        tp = "tp" if "tp" in am.axis_names and am.shape["tp"] > 1 else None
        manual |= {ax for ax in (dp, tp) if ax}
    spec = P(dp, cfg.sp_axis, tp)       # (B, S, H, D)
    mask_spec = P(dp, cfg.sp_axis)      # (B, S)

    if mask is None:
        fn = shard_map(
            lambda q, k, v: impl(q, k, v, cfg.sp_axis, causal=cfg.causal),
            mesh=am,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names=manual,
        )
        return fn(q, k, v)
    # Padding mask rides sequence-sharded like K/V; each kernel handles
    # distribution itself (ring rotates it, Ulysses all-gathers it).
    fn = shard_map(
        lambda q, k, v, m: impl(q, k, v, cfg.sp_axis, causal=cfg.causal,
                                mask=m),
        mesh=am,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec,
        axis_names=manual,
    )
    return fn(q, k, v, mask)


class MultiHeadAttention(nn.Module):
    """MXU-friendly attention: fused QKV projection, einsum contractions,
    softmax in fp32. Head dim carries the "heads" logical axis so tensor
    parallelism (Megatron-style column/row split) is a sharding rule, and
    the (batch, seq) activation constraint lets dp/sp shard the sequence
    (the jit-visible face of sequence parallelism; ring attention lives
    in parallel/ring.py for shard_map use)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask: Optional[jax.Array] = None,
                 deterministic: bool = True):
        cfg = self.cfg
        B, S, D = x.shape
        H, Hd = cfg.n_heads, cfg.head_dim

        qkv = nn.DenseGeneral(
            (3, H, Hd),
            axis=-1,
            use_bias=True,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                default_kernel_init, ("embed", None, "heads", "kv")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, "heads", "kv")
            ),
            name="qkv",
        )(x)
        q, k, v = (jnp.squeeze(a, axis=2)
                   for a in jnp.split(qkv, 3, axis=2))  # (B,S,H,Hd)
        q = nn.with_logical_constraint(q, ("batch", "seq", "heads", "kv"))
        k = nn.with_logical_constraint(k, ("batch", "seq", "heads", "kv"))
        v = nn.with_logical_constraint(v, ("batch", "seq", "heads", "kv"))

        ctx = _attention_dispatch(cfg, q, k, v, mask)
        ctx = nn.with_logical_constraint(ctx, ("batch", "seq", "heads", "kv"))

        out = nn.DenseGeneral(
            D,
            axis=(-2, -1),
            use_bias=True,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                default_kernel_init, ("heads", "kv", "embed")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed",)
            ),
            name="out",
        )(ctx)
        return nn.with_logical_constraint(out, ("batch", "seq", "embed"))


class MlpBlock(nn.Module):
    """Dense FFN: d_model → d_ff (column-split "mlp") → d_model (row-split)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        h = _dense(cfg.d_ff, cfg, "wi", ("embed", "mlp"))(x)
        h = nn.gelu(h)
        h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg.d_model, cfg, "wo", ("mlp", "embed"))(h)
        if cfg.dropout_rate > 0.0:
            h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return nn.with_logical_constraint(h, ("batch", "seq", "embed"))


class SwitchMoE(nn.Module):
    """Switch-transformer top-1 MoE FFN with static capacity.

    Expert weights carry the "expert" logical axis — map it to the mesh's
    ep axis and XLA inserts the all-to-all dispatch (the reference's
    `hvd.alltoall` is exactly this primitive; SURVEY.md §2.6 notes MoE as
    an absent-but-enabled strategy there). Dispatch/combine are one-hot
    einsums: static shapes, MXU-friendly, drop-on-overflow.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        B, S, D = x.shape
        E = cfg.n_experts
        T = B * S
        C = max(1, int(cfg.capacity_factor * T / E))  # per-expert capacity

        tokens = x.reshape(T, D)
        gate_logits = _dense(E, cfg, "router", ("embed", None), use_bias=False)(
            tokens
        ).astype(jnp.float32)
        probs = jax.nn.softmax(gate_logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)                  # (T,)
        gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)   # (T,E)
        pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot - 1   # (T,E)
        pos = jnp.max(pos_in_expert, axis=-1)                     # (T,)
        keep = (pos >= 0) & (pos < C)

        # dispatch: (T, E, C) one-hot; combine adds the gate weight.
        dispatch = (
            jax.nn.one_hot(expert_idx, E, dtype=cfg.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, -1), C, dtype=cfg.dtype)[:, None, :]
        )
        expert_in = jnp.einsum("td,tec->ecd", tokens.astype(cfg.dtype), dispatch)
        expert_in = nn.with_logical_constraint(expert_in, ("expert", None, "embed"))

        wi = self.param(
            "wi",
            nn.with_logical_partitioning(default_kernel_init,
                                         ("expert", "embed", "expert_mlp")),
            (E, D, cfg.d_ff),
            cfg.param_dtype,
        )
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(default_kernel_init,
                                         ("expert", "expert_mlp", "embed")),
            (E, cfg.d_ff, D),
            cfg.param_dtype,
        )
        h = jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(cfg.dtype))
        h = nn.gelu(h)
        h = nn.with_logical_constraint(h, ("expert", None, "expert_mlp"))
        expert_out = jnp.einsum("ecf,efd->ecd", h, wo.astype(cfg.dtype))

        combine = dispatch * gate.astype(cfg.dtype)[:, None, None]
        out = jnp.einsum("ecd,tec->td", expert_out, combine)
        # Router auxiliary load-balancing loss (Switch eq. 4), stashed for
        # the train step to pick up via mutable "losses" collection.
        density = jnp.mean(onehot.astype(jnp.float32), axis=0)
        density_proxy = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(density * density_proxy)
        self.sow("losses", "moe_aux", aux)
        return out.reshape(B, S, D)


class TransformerBlock(nn.Module):
    """Pre-LN block; `use_moe` swaps the FFN for SwitchMoE. When
    `scanned` the return is the (carry, ys) pair nn.scan requires."""

    cfg: TransformerConfig
    use_moe: bool = False
    scanned: bool = False

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        cfg = self.cfg
        ln = functools_partial_ln(cfg)
        h = x + MultiHeadAttention(cfg, name="attn")(
            ln(name="ln1")(x), mask, deterministic
        )
        ffn: nn.Module
        if self.use_moe:
            ffn = SwitchMoE(cfg, name="moe")
        else:
            ffn = MlpBlock(cfg, name="mlp")
        out = h + ffn(ln(name="ln2")(h), deterministic)
        out = nn.with_logical_constraint(out, ("batch", "seq", "embed"))
        return (out, None) if self.scanned else out


def functools_partial_ln(cfg: TransformerConfig):
    import functools

    return functools.partial(
        nn.LayerNorm,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        scale_init=nn.with_logical_partitioning(nn.initializers.ones_init(),
                                                ("embed",)),
        bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                               ("embed",)),
    )


def sinusoidal_positions(max_len: int, d_model: int) -> np.ndarray:
    pos = np.arange(max_len)[:, None]
    div = np.exp(np.arange(0, d_model, 2) * (-np.log(10000.0) / d_model))
    pe = np.zeros((max_len, d_model), dtype=np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div)
    return pe


class Embedder(nn.Module):
    """Token + position embedding; vocab dim is tp-shardable ("vocab")."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, ids):
        cfg = self.cfg
        emb = self.param(
            "embedding",
            nn.with_logical_partitioning(default_kernel_init, ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model),
            cfg.param_dtype,
        )
        x = jnp.take(emb, ids, axis=0).astype(cfg.dtype)
        if cfg.learned_pos:
            pos_emb = self.param(
                "pos_embedding",
                nn.with_logical_partitioning(default_kernel_init, (None, "embed")),
                (cfg.max_len, cfg.d_model),
                cfg.param_dtype,
            )
            x = x + pos_emb[None, : ids.shape[1]].astype(cfg.dtype)
        else:
            pe = sinusoidal_positions(cfg.max_len, cfg.d_model)
            x = x + jnp.asarray(pe[None, : ids.shape[1]], dtype=cfg.dtype)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))

    def attend(self, x):
        emb = self.get_variable("params", "embedding")
        return jnp.einsum("bsd,vd->bsv", x, emb.astype(x.dtype))


class TransformerStack(nn.Module):
    """The n_layers block stack; optionally nn.scan'd and/or remat'd."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        cfg = self.cfg
        block_cls = TransformerBlock
        if cfg.remat:
            block_cls = nn.remat(
                TransformerBlock,
                prevent_cse=not cfg.scan_layers,
                static_argnums=(3,),
            )
        if cfg.scan_layers and cfg.n_experts == 0:
            # Homogeneous stack → scan for O(1) compile; params gain a
            # leading "layers" axis.
            ScannedBlock = nn.scan(
                block_cls,
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = ScannedBlock(cfg, False, True, name="layers")(
                x, mask, deterministic
            )
            return x
        for i in range(cfg.n_layers):
            use_moe = (
                cfg.n_experts > 0
                and cfg.moe_every > 0
                and (i % cfg.moe_every == cfg.moe_every - 1)
            )
            x = block_cls(cfg, use_moe, name=f"layer_{i}")(x, mask, deterministic)
        return x


class TransformerLM(nn.Module):
    """Decoder-only causal LM — the GPT-2 shape (flagship model).

    Parity target: reference north-star "GPT-2 1.3B + Adasum grad
    aggregation" (BASELINE.json; SURVEY.md §6)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, ids, mask=None, deterministic: bool = True):
        cfg = self.cfg
        embedder = Embedder(cfg, name="embed")
        x = embedder(ids)
        x = TransformerStack(cfg, name="stack")(x, mask, deterministic)
        x = functools_partial_ln(cfg)(name="ln_f")(x)
        if cfg.logits_via_embedding:
            logits = embedder.attend(x)
        else:
            logits = _dense(cfg.vocab_size, cfg, "lm_head", ("embed", "vocab"),
                            use_bias=False)(x)
        return nn.with_logical_constraint(
            logits.astype(cfg.logits_dtype), ("batch", "seq", "vocab")
        )


class TransformerEncoder(nn.Module):
    """Bidirectional encoder + MLM head — the BERT shape.

    Parity target: reference north-star "BERT-base DistributedGradientTape
    + tensor fusion" (BASELINE.json)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, ids, mask=None, deterministic: bool = True):
        cfg = dataclasses.replace(self.cfg, causal=False)
        x = Embedder(cfg, name="embed")(ids)
        x = TransformerStack(cfg, name="stack")(x, mask, deterministic)
        x = functools_partial_ln(cfg)(name="ln_f")(x)
        logits = _dense(cfg.vocab_size, cfg, "mlm_head", ("embed", "vocab"),
                        use_bias=False)(x)
        return logits.astype(cfg.logits_dtype)


# ---------------------------------------------------------------------------
# Named configs (sizes follow the public GPT-2 / BERT model cards).
GPT2_CONFIGS = {
    "gpt2-tiny": TransformerConfig(vocab_size=1024, d_model=128, n_heads=4,
                                   n_layers=2, d_ff=512, max_len=256),
    "gpt2-small": TransformerConfig(d_model=768, n_heads=12, n_layers=12,
                                    d_ff=3072),
    "gpt2-medium": TransformerConfig(d_model=1024, n_heads=16, n_layers=24,
                                     d_ff=4096),
    "gpt2-large": TransformerConfig(d_model=1280, n_heads=20, n_layers=36,
                                    d_ff=5120),
    "gpt2-xl": TransformerConfig(d_model=1600, n_heads=25, n_layers=48,
                                 d_ff=6400),
    # The north-star 1.3B config (GPT-3 XL shape).
    "gpt2-1p3b": TransformerConfig(d_model=2048, n_heads=16, n_layers=24,
                                   d_ff=8192, max_len=2048),
}

BERT_CONFIGS = {
    "bert-tiny": TransformerConfig(vocab_size=30522, d_model=128, n_heads=2,
                                   n_layers=2, d_ff=512, max_len=128,
                                   causal=False),
    "bert-base": TransformerConfig(vocab_size=30522, d_model=768, n_heads=12,
                                   n_layers=12, d_ff=3072, max_len=512,
                                   causal=False),
    "bert-large": TransformerConfig(vocab_size=30522, d_model=1024, n_heads=16,
                                    n_layers=24, d_ff=4096, max_len=512,
                                    causal=False),
}
