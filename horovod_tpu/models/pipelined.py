"""PipelinedLM: the flagship causal LM with its layer stack run as a
GPipe pipeline over the pp mesh axis.

Pipeline parallelism is absent from the reference (SURVEY.md §2.6); this
is the TPU-native construction: the scan-stacked layer parameters
("layers" leading dim) are regrouped into pp stages, sharded over the pp
axis, and driven by `parallel.pipeline.gpipe` (shard_map manual on pp
only — dp/sp/tp inside each stage remain GSPMD). Duck-types the flax
`init/apply` pair so `make_train_step` drives it unchanged.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..parallel.pipeline import gpipe, stack_stage_params
from .transformer import (
    Embedder,
    TransformerBlock,
    TransformerConfig,
    TransformerLM,
    functools_partial_ln,
)


class PipelinedLM:
    """Wraps TransformerLM (scan_layers=True, dense FFN) with a pipelined
    apply. Parameters are bit-identical to the unpipelined model, so
    checkpoints interchange."""

    def __init__(self, cfg: TransformerConfig, mesh, axis: str = "pp",
                 num_microbatches: Optional[int] = None):
        if not cfg.scan_layers or cfg.n_experts:
            raise ValueError(
                "PipelinedLM needs scan_layers=True and a dense FFN "
                "(stage params must stack homogeneously)"
            )
        if cfg.n_layers % mesh.shape[axis] != 0:
            raise ValueError(
                f"n_layers={cfg.n_layers} not divisible by pp="
                f"{mesh.shape[axis]}"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.num_microbatches = num_microbatches
        self.inner = TransformerLM(cfg)

    def init(self, rng, ids, **kwargs):
        return self.inner.init(rng, ids, **kwargs)

    def apply(self, variables, ids, **kwargs):
        cfg = self.cfg
        params = variables["params"]
        S = self.mesh.shape[self.axis]

        x = Embedder(cfg, name=None).apply({"params": params["embed"]}, ids)

        stage_params = stack_stage_params(params["stack"]["layers"], S)
        block = TransformerBlock(cfg, use_moe=False, scanned=True)

        def stage_fn(p, act):
            # p: per-stage layer stack (L/S leading dim); scan the local
            # layers sequentially.
            def body(carry, pl):
                y, _ = block.apply({"params": pl}, carry, None, True)
                return y, None

            act, _ = jax.lax.scan(body, act, p)
            return act

        x = gpipe(stage_fn, stage_params, x, mesh=self.mesh, axis=self.axis,
                  num_microbatches=self.num_microbatches)

        ln = functools_partial_ln(cfg)()
        x = ln.apply({"params": params["ln_f"]}, x)
        if cfg.logits_via_embedding:
            emb = params["embed"]["embedding"]
            logits = jnp.einsum("bsd,vd->bsv", x, emb.astype(x.dtype))
        else:
            kernel = params["lm_head"]["kernel"]
            logits = jnp.einsum("bsd,dv->bsv", x, kernel.astype(x.dtype))
        return logits.astype(cfg.logits_dtype)
