"""Model zoo: the reference's benchmark/example workloads as TPU-first
flax models (SURVEY.md §6 / BASELINE.json north-star configs)."""
from .mnist import MnistCNN, MnistMLP
from .registry import REGISTRY, ModelSpec, get_model, list_models
from .resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from .transformer import (
    BERT_CONFIGS,
    GPT2_CONFIGS,
    SwitchMoE,
    TransformerConfig,
    TransformerEncoder,
    TransformerLM,
)
from .vit import VIT_CONFIGS, ViT, ViTConfig
