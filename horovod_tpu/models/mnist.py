"""MNIST models — parity target: the reference's first-run examples
(`examples/tensorflow2_mnist.py`, `examples/pytorch_mnist.py`; PR1 config
in BASELINE.json is the 2-process CPU MNIST equivalent)."""
from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MnistMLP(nn.Module):
    features: Sequence[int] = (128, 64)
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1).astype(self.dtype)
        for f in self.features:
            x = nn.relu(nn.Dense(f, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class MnistCNN(nn.Module):
    """The LeNet-ish conv net the reference's torch MNIST example uses
    (examples/pytorch_mnist.py Net: conv 10 → conv 20 → fc 50 → fc 10)."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(50, dtype=self.dtype)(x))
        if not deterministic:
            x = nn.Dropout(0.5)(x, deterministic=False)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
