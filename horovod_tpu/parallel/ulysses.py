"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

DeepSpeed-Ulysses pattern built on the collective the reference exposes
as `hvd.alltoall` (SURVEY.md §2.6 names it the enabling primitive for
SP): q/k/v arrive sharded on the sequence dim; one all-to-all re-shards
them on the head dim with the full sequence local, dense attention runs
per head group, and a second all-to-all restores sequence sharding.
Cheaper than ring attention when heads ≥ sp and the sequence fits HBM;
ring attention wins at extreme context lengths.

Use inside shard_map with the sp axis manual.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.compat import axis_size as _axis_size

from .ring import dense_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    mask: Optional[jax.Array] = None,
    use_flash: bool = False,
) -> jax.Array:
    """q/k/v: local blocks (B, S/n, H, D); H must divide by the axis
    size. Returns (B, S/n, H, D). `mask` is this rank's key-validity
    block (B, S/n); the head-sharded dense attention needs the full
    sequence's mask, so it is all-gathered along the sp axis (tiny:
    one bit per token).

    `use_flash` swaps the per-head-group dense attention for the Pallas
    flash kernel (ops/flash_attention.py) — after the head exchange the
    full sequence is local, exactly the kernel's layout, so the fused
    path composes with sequence parallelism for free."""
    n = _axis_size(axis_name)
    H = q.shape[2]
    if H % n != 0:
        raise ValueError(f"n_heads={H} must be divisible by sp={n}")

    def seq_to_heads(x):
        # (B, S/n, H, D) → (B, S, H/n, D): split heads, gather sequence.
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    full_mask = None
    if mask is not None:
        full_mask = jax.lax.all_gather(mask, axis_name, axis=1, tiled=True)
    if use_flash:
        from ..ops.flash_attention import flash_attention

        out = flash_attention(qh, kh, vh, full_mask, causal=causal)
    else:
        out = dense_attention(qh, kh, vh, causal=causal, mask=full_mask)
    return heads_to_seq(out)
