"""wrap_step: run a training-step function SPMD over the mesh.

This is the TPU-native answer to "wrap your optimizer and your script
scales" (ref: README.rst:80-99): the user writes a single-chip step
function that calls hvd.allreduce (or uses hvd.DistributedOptimizer);
`wrap_step` shard_maps it over the data axis so each chip sees its batch
shard, hvd collectives bind to the mesh axis, and XLA compiles one SPMD
program with ICI collectives — no background thread, no negotiation.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common import basics
from ..utils.compat import shard_map


def wrap_step(
    fn: Callable = None,
    *,
    mesh=None,
    axis_name: Optional[str] = None,
    sharded_argnums: Optional[Sequence[int]] = None,
    replicated_argnums: Sequence[int] = (0,),
    out_replicated: bool = True,
    jit: bool = True,
    donate_argnums: Tuple[int, ...] = (),
):
    """Decorate a step function for SPMD execution.

    By default argument 0 (params / train state) is replicated and every
    other argument is sharded along its leading (batch) dim; the output
    is replicated (gradients inside should already be allreduced via
    hvd.allreduce / DistributedOptimizer — shard_map will verify
    replication only where cheap).

    Usage:
        @hvd.wrap_step
        def train_step(state, batch): ...
    """
    if fn is None:
        return functools.partial(
            wrap_step,
            mesh=mesh,
            axis_name=axis_name,
            sharded_argnums=sharded_argnums,
            replicated_argnums=replicated_argnums,
            out_replicated=out_replicated,
            jit=jit,
            donate_argnums=donate_argnums,
        )

    # Compiled-step cache: jax.jit caches on function identity, so the
    # shard_map/jit construction must happen once per (mesh, arg
    # structure/shape/dtype) signature, not per call — otherwise every
    # training step would re-trace.
    cache = {}

    @functools.wraps(fn)
    def wrapped(*args):
        m = mesh if mesh is not None else basics.mesh()
        an = axis_name if axis_name is not None else basics.axis_name()
        if m is None:
            raise RuntimeError("wrap_step requires mesh mode (hvd.init())")
        leaves, treedef = jax.tree.flatten(args)
        key = (
            id(m), treedef,
            tuple((getattr(l, "shape", ()), str(getattr(l, "dtype", type(l))))
                  for l in leaves),
        )
        sm = cache.get(key)
        if sm is None:
            repl = set(replicated_argnums)
            if sharded_argnums is not None:
                shard = set(sharded_argnums)
                repl = set(range(len(args))) - shard
            in_specs = tuple(
                jax.tree.map(lambda _: P() if i in repl else P(an), args[i])
                for i in range(len(args))
            )
            out_spec = P() if out_replicated else P(an)

            # Mark replicated inputs as axis-varying inside the body.
            # Without this, jax's manual-axes tracking auto-psums the
            # cotangent of any replicated input, so a user's jax.grad
            # inside the step already returns the cross-rank SUM and a
            # subsequent hvd.allreduce(AVERAGE) cannot recover the
            # per-rank average (it sees identical values on every
            # shard). pvary keeps grads rank-local — the reference's
            # semantics, where each rank owns its gradient until the
            # explicit allreduce (ref: horovod/torch/optimizer.py:114-149).
            def local_fn(*inner):
                from ..utils.compat import pvary

                inner = tuple(
                    jax.tree.map(lambda x: pvary(x, an), a)
                    if i in repl else a
                    for i, a in enumerate(inner)
                )
                return fn(*inner)

            # out_specs is a prefix pytree: one spec covers the whole
            # output tree (eval_shape-ing fn here would trace its
            # collectives outside the mesh and hit unbound axis names).
            sm = shard_map(
                local_fn, mesh=m,
                in_specs=in_specs,
                out_specs=out_spec,
            )
            if jit:
                sm = jax.jit(sm, donate_argnums=donate_argnums)
            cache[key] = sm
        return sm(*args)

    return wrapped
