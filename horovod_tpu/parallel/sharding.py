"""Logical-axis → mesh-axis sharding rules and helpers.

This is the TPU-native replacement for the reference's rank-topology +
backend-selection machinery (SURVEY.md §2.6/§5.8): instead of choosing
NCCL vs MPI per op, you choose *where each named tensor dimension lives
on the mesh*, and XLA inserts the collectives (psum for row-parallel
matmuls, all-to-all for expert dispatch, ...) over ICI/DCN.

Models in horovod_tpu.models annotate parameters and activations with
logical axis names ("embed", "mlp", "heads", "expert", ...). The rules
below map those to the canonical mesh axes (parallel/mesh.py AXIS_ORDER:
pp, dp, ep, sp, tp). Users override per-call for custom layouts.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (logical axis, mesh axes) pairs. A logical axis maps to the first rule
# whose mesh axes are all present in the mesh (flax skips absent axes).
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dp",)),          # batch dim → data parallel
    ("seq", ("sp",)),            # sequence dim → context parallel
    ("embed", None),             # d_model replicated (megatron layout)
    ("mlp", ("tp",)),            # d_ff column-split
    ("heads", ("tp",)),          # attention heads split
    ("kv", None),
    ("vocab", ("tp",)),          # embedding/lm-head vocab split
    ("expert", ("ep",)),         # MoE experts → expert parallel
    ("expert_mlp", ("tp",)),
    ("layers", None),            # scan axis; "pp" when pipeline-sharding
    ("stage", ("pp",)),
)

# Pipeline variant: scan-stacked layer params shard over pp on their
# leading "layers" axis (PipelinedLM regroups them into stages).
PIPELINE_RULES: Tuple[Tuple[str, Any], ...] = tuple(
    ("layers", ("pp",)) if k == "layers" else (k, v) for k, v in DEFAULT_RULES
)

# FSDP-style variant: shard the big replicated dims over dp as well
# (ZeRO-3 analogue — the reference has no equivalent; TPU-native bonus).
FSDP_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dp",)),
    ("seq", ("sp",)),
    ("embed", ("dp",)),
    ("mlp", ("tp",)),
    ("heads", ("tp",)),
    ("kv", None),
    ("vocab", ("tp",)),
    ("expert", ("ep",)),
    ("expert_mlp", ("tp",)),
    ("layers", None),
    ("stage", ("pp",)),
)


def filter_rules(rules: Sequence[Tuple[str, Any]], mesh: Mesh):
    """Drop mesh axes that don't exist in `mesh` (so one rule set serves
    a dp-only mesh and a full pp×dp×ep×sp×tp mesh)."""
    out = []
    for logical, axes in rules:
        if axes is None:
            out.append((logical, None))
            continue
        if isinstance(axes, str):
            axes = (axes,)
        present = tuple(a for a in axes if a in mesh.axis_names)
        if len(present) == 1:
            out.append((logical, present[0]))
        elif present:
            out.append((logical, present))
        else:
            out.append((logical, None))
    return tuple(out)


def logical_sharding(tree, mesh: Mesh, rules=DEFAULT_RULES):
    """Boxed (LogicallyPartitioned) pytree → NamedSharding pytree."""
    specs = nn.get_partition_spec(tree)
    return nn.logical_to_mesh_sharding(specs, mesh, filter_rules(rules, mesh))


def init_sharded(model, rng, example_inputs, mesh: Mesh, rules=DEFAULT_RULES,
                 **init_kwargs):
    """Initialize model variables directly into their mesh shardings
    (no host round-trip; params larger than one host's RAM stay sharded).

    Returns (variables, shardings) with variables *unboxed* (plain
    arrays, metadata stripped) — downstream code uses the shardings tree.
    """
    abstract = jax.eval_shape(
        lambda r: model.init(r, *example_inputs, **init_kwargs), rng
    )
    # get_partition_spec collapses metadata boxes to PartitionSpec leaves,
    # so the sharding tree matches the *unboxed* variable structure.
    shardings = logical_sharding(abstract, mesh, rules)
    init_fn = jax.jit(
        lambda r: nn.unbox(model.init(r, *example_inputs, **init_kwargs)),
        out_shardings=shardings,
    )
    variables = init_fn(rng)
    return variables, shardings


def batch_spec(mesh: Mesh, shard_seq: bool = False) -> P:
    """PartitionSpec for an input batch: leading dim over dp (and pp's
    microbatch dim is handled by the pipeline layer), sequence dim over
    sp when requested."""
    b = tuple(a for a in ("dp",) if a in mesh.axis_names) or None
    if not shard_seq:
        return P(b)
    s = tuple(a for a in ("sp",) if a in mesh.axis_names) or None
    return P(b, s)
