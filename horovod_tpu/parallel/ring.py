"""Ring attention: blockwise context parallelism over the sp mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §5.7 —
its `alltoall` is the closest primitive). Sequence is sharded across sp
ranks; K/V blocks rotate around the ring via `lax.ppermute` while each
rank folds them into a streaming-softmax accumulator (flash-attention
style m/l/o state), so attention memory is O(S/n) per chip and the
K/V transfer rides ICI neighbor links — the layout ppermute maps to
natively on a TPU torus.

Use inside shard_map with the sp axis manual, e.g. via
`horovod_tpu.parallel.step.wrap_step` or a custom shard_map; q/k/v enter
as local sequence blocks (B, S/n, H, D).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compat import axis_index as _axis_index, axis_size as _axis_size


def _flash_block_update(o, m, l, q, k, v, qpos, kpos, scale, causal,
                        kmask=None):
    """Fold one K/V block into the streaming-softmax state.

    o: (B, Sq, H, D) f32 accumulated (unnormalized) output
    m, l: (B, H, Sq) f32 running max / normalizer
    kmask: optional (B, Sk) key-validity block (1 = attend, 0 = pad)

    Invalid probabilities are zeroed explicitly (not just pushed to
    -1e30 in the scores): when an entire block is masked, exp(s - m_new)
    would otherwise collapse to exp(0)=1 for every masked entry and
    poison l — explicit zeroing keeps the accumulator exact for any
    mask pattern, including all-padding blocks.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    valid = None
    if causal:
        valid = (kpos[None, :] <= qpos[:, None])[None, None]  # (1,1,Sq,Sk)
    if kmask is not None:
        km = kmask.astype(bool)[:, None, None, :]             # (B,1,1,Sk)
        valid = km if valid is None else jnp.logical_and(valid, km)
    if valid is not None:
        s = jnp.where(valid, s, -1e30)
    m_blk = jnp.max(s, axis=-1)                      # (B,H,Sq)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new[..., None])                # (B,H,Sq,Sk)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    corr = jnp.exp(m - m_new)                        # (B,H,Sq)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention over the global sequence with q/k/v sharded on dim 1
    across `axis_name`. Returns the local output block (B, S/n, H, D) in
    q.dtype. Differentiable (used in training steps).

    `mask` is this rank's key-validity block (B, S/n), 1 = attend,
    0 = pad; it rotates around the ring with its K/V block. Fully-padded
    query rows produce zeros (their normalizer is clamped), the BERT
    convention — the loss must mask them anyway."""
    n = _axis_size(axis_name)
    idx = _axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / np.sqrt(D)
    qpos = idx * Sq + jnp.arange(Sq)

    o = jnp.zeros((B, Sq, H, D), jnp.float32)
    m = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Sq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    kmask = None if mask is None else mask.astype(jnp.float32)

    def body(carry, t):
        o, m, l, k, v, km = carry
        # After t rotations this rank holds the block that started at
        # rank (idx - t) mod n.
        src = (idx - t) % n
        kpos = src * Sk + jnp.arange(Sk)
        o, m, l = _flash_block_update(o, m, l, q, k, v, qpos, kpos, scale,
                                      causal, kmask=km)
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        if km is not None:
            km = jax.lax.ppermute(km, axis_name, perm)
        return (o, m, l, k, v, km), None

    (o, m, l, _, _, _), _ = jax.lax.scan(body, (o, m, l, k, v, kmask),
                                         jnp.arange(n))
    # Clamp the normalizer: fully-masked rows have l == 0 (and o == 0),
    # so they come out as zeros instead of NaN.
    l_safe = jnp.maximum(l, 1e-30)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def dense_attention(q, k, v, causal: bool = True,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """Single-device reference attention (same layout, no sharding).
    `mask`: optional (B, Sk) key validity, 1 = attend, 0 = pad."""
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
    valid = None
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        valid = (jnp.arange(Sk)[None, :]
                 <= jnp.arange(Sq)[:, None])[None, None]
    if mask is not None:
        km = mask.astype(bool)[:, None, None, :]
        valid = km if valid is None else jnp.logical_and(valid, km)
    if valid is not None:
        s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if valid is not None:
        # Zero masked probabilities so fully-masked rows yield 0, not a
        # uniform distribution over -1e30 logits (matches the ring
        # kernel's convention).
        p = jnp.where(valid, p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(q.dtype)
