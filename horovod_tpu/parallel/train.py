"""Sharded training-step construction — the GSPMD fast path.

The reference scales training by wrapping the optimizer so each grad is
allreduced by the background engine (horovod/torch/optimizer.py:32-207).
The TPU-native equivalent: build ONE jitted SPMD train step where the
batch is sharded over dp(/sp) and params over the rule-mapped axes; XLA
then *derives* the gradient all-reduce (and any tp psums / ep
all-to-alls) from the shardings — fused, overlapped with compute, on
ICI. This file is that construction.

The name-negotiated async engine remains for eager/process mode; under
jit the static op set is the "response cache 100% hit" regime the
reference only reaches in steady state (controller.cc:174-203).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import DEFAULT_RULES, batch_spec, filter_rules, logical_sharding
from ..utils.compat import (set_mesh as _set_mesh,
                            tree_leaves_with_path as _tree_leaves_with_path)


@dataclasses.dataclass
class TrainState:
    """Minimal train state (params, opt_state, step) as a pytree."""

    step: Any
    params: Any
    opt_state: Any
    extra: Any = None  # e.g. batch_stats for BN models

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state, self.extra), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


def softmax_xent(logits, labels) -> jax.Array:
    """Mean cross-entropy; logits fp32 (softmax numerics on TPU).

    The one-hot inner product is deliberate: XLA fuses one_hot into
    the reduction (a compare-select epilogue — the (B,S,V) one-hot is
    never materialized), while take_along_axis lowers to a TPU gather
    that measures 12-20% SLOWER on the loss at both BERT and GPT-2
    bench shapes (v5e, fwd+bwd in-jit loops, r4)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def lm_loss(logits, ids) -> jax.Array:
    """Next-token prediction loss for causal LMs."""
    return softmax_xent(logits[:, :-1], ids[:, 1:])


def make_train_step(
    model: nn.Module,
    tx: optax.GradientTransformation,
    loss_fn: Callable,
    *,
    mesh: Mesh,
    rules=DEFAULT_RULES,
    shard_seq: bool = False,
    has_batch_stats: bool = False,
    moe_aux_weight: float = 0.0,
    donate: bool = True,
    dropout: bool = False,
    dropout_seed: int = 0,
    zero: bool = False,
):
    """Returns (init_state_fn, train_step_fn), both jitted with explicit
    in/out shardings over `mesh`.

    loss_fn(logits, batch_labels) -> scalar. The model's first input is
    batch[0]; labels are batch[1] (or batch[0] again for LMs).

    `dropout=True` runs the model with deterministic=False and threads a
    per-step dropout rng (folded from `dropout_seed` and the step
    counter). Leave False for models without dropout — with it False,
    any configured dropout_rate is inactive during training.

    `zero=True` is the GSPMD spelling of ZeRO (docs/running.md "ZeRO
    sharded optimizer state"): optimizer-state moments are given a
    NamedSharding over the dp axis (dim 0, when divisible) instead of
    mirroring their param's sharding, and XLA derives the
    reduce-scatter → sharded update → allgather schedule from the
    sharding constraint alone — no optimizer wrapper, and it composes
    with tp/sp rules because only the DATA axis is re-used.
    """
    rules = filter_rules(rules, mesh)
    repl = NamedSharding(mesh, P())
    zero_axis = "dp" if "dp" in mesh.axis_names else None
    if zero and zero_axis is None:
        raise ValueError(
            "make_train_step(zero=True) needs a 'dp' axis in the mesh "
            "to shard optimizer state over")

    def _batch_sharding(arg) -> NamedSharding:
        # Leading dim over dp; dim 1 over sp for rank≥2 inputs when
        # sequence sharding is on; everything else replicated.
        ndim = getattr(arg, "ndim", 0)
        if ndim == 0:
            return repl
        if shard_seq and ndim >= 2:
            return NamedSharding(mesh, batch_spec(mesh, True))
        return NamedSharding(mesh, batch_spec(mesh, False))

    def init_state(rng, *example_inputs) -> TrainState:
        variables = model.init(rng, *example_inputs)
        variables = nn.unbox(variables)
        params = variables["params"]
        extra = (
            {k: v for k, v in variables.items() if k != "params"}
            if has_batch_stats else None
        )
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            extra=extra,
        )

    # Shardings for the state: params via logical rules, opt state maps
    # each param's sharding onto its moment tensors (same shape ⇒ same
    # sharding), scalars replicated.
    def state_shardings(rng, *example_inputs):
        # One abstract trace of model.init serves the param shardings, the
        # unboxed param tree, and (via tx.init on abstract params) the
        # optimizer-state structure.
        abstract = jax.eval_shape(
            lambda r: model.init(r, *example_inputs), rng
        )
        # get_partition_spec collapses metadata boxes to PartitionSpec
        # leaves, so pshard matches the *unboxed* param structure.
        pshard = logical_sharding(abstract, mesh, rules)["params"]
        abstract_unboxed = nn.unbox(abstract)
        abstract_params = abstract_unboxed["params"]
        abstract_opt = jax.eval_shape(tx.init, abstract_params)
        abstract_extra = (
            {k: v for k, v in abstract_unboxed.items() if k != "params"}
            if has_batch_stats else None
        )

        # Build opt-state shardings by structural mapping: any leaf whose
        # shape matches a param leaf gets that param's sharding, else
        # replicated. optax states are pytrees of param-shaped moments.
        flat_params = _tree_leaves_with_path(abstract_params)
        flat_pshard = _tree_leaves_with_path(pshard)
        pmap_by_path = {
            jax.tree_util.keystr(kp): s
            for (kp, _), (_, s) in zip(flat_params, flat_pshard)
        }

        # Longest-suffix match so "['wi']['kernel']" can't shadow
        # "['mlp']['wi']['kernel']".
        by_len = sorted(pmap_by_path.items(), key=lambda kv: -len(kv[0]))

        ndp = mesh.shape.get("dp", 1) if zero else 1

        def opt_shard(path, leaf):
            ks = jax.tree_util.keystr(path)
            # optax wraps param trees: strip prefixes like .0.mu / .1 etc.
            for ppath, s in by_len:
                if ks.endswith(ppath):
                    if (zero and leaf.ndim >= 1
                            and leaf.shape[0] % ndp == 0
                            and leaf.shape[0] >= ndp):
                        # ZeRO: moments shard over dp on dim 0, stacked
                        # in front of the param's own (tp/...) spec —
                        # the reduce-scatter/allgather is derived by
                        # XLA from this constraint.
                        spec = s.spec if hasattr(s, "spec") else P()
                        rest = tuple(spec)[1:] if len(spec) else ()
                        dim0 = tuple(spec)[0] if len(spec) else None
                        if dim0 is None:
                            return NamedSharding(
                                mesh, P(zero_axis, *rest))
                        if (isinstance(dim0, str) and dim0 != zero_axis
                                and leaf.shape[0] % (
                                    ndp * mesh.shape[dim0]) == 0):
                            return NamedSharding(
                                mesh, P((dim0, zero_axis), *rest))
                        return s
                    return s
            return repl

        opt_sh = jax.tree_util.tree_map_with_path(opt_shard, abstract_opt)
        extra_sh = (
            jax.tree.map(lambda _: repl, abstract_extra)
            if abstract_extra is not None else None
        )
        return TrainState(step=repl, params=pshard, opt_state=opt_sh,
                          extra=extra_sh)

    def train_step(state: TrainState, *batch):
        inputs, labels = batch[0], batch[-1]

        def compute_loss(params):
            variables = {"params": params}
            mutable = []
            if state.extra:
                variables.update(state.extra)
                mutable = list(state.extra.keys())
            if moe_aux_weight > 0.0:
                mutable = mutable + ["losses"]
            kwargs = {}
            if has_batch_stats:
                kwargs["train"] = True
            elif _accepts_deterministic(model):
                kwargs["deterministic"] = not dropout
            if dropout:
                kwargs["rngs"] = {
                    "dropout": jax.random.fold_in(
                        jax.random.PRNGKey(dropout_seed), state.step
                    )
                }
            if mutable:
                logits, updates = model.apply(
                    variables, inputs, mutable=mutable, **kwargs
                )
            else:
                logits = model.apply(variables, inputs, **kwargs)
                updates = {}
            loss = loss_fn(logits, labels)
            if moe_aux_weight > 0.0 and "losses" in updates:
                aux = sum(jnp.sum(jnp.asarray(v))
                          for v in jax.tree.leaves(updates["losses"]))
                loss = loss + moe_aux_weight * aux
            new_extra = {k: v for k, v in updates.items() if k != "losses"}
            return loss, new_extra

        (loss, new_extra), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(state.params)
        upd, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, upd)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt,
            extra=new_extra if state.extra else state.extra,
        )
        return new_state, loss

    def build(rng, *example_batch):
        model_inputs = example_batch[:1]
        with _set_mesh(mesh):
            ssh = state_shardings(rng, *model_inputs)
        init_jit = jax.jit(
            lambda r: init_state(r, *model_inputs), out_shardings=ssh
        )
        bsh = tuple(_batch_sharding(a) for a in example_batch)
        step_jit = jax.jit(
            train_step,
            in_shardings=(ssh,) + bsh,
            out_shardings=(ssh, repl),
            donate_argnums=(0,) if donate else (),
        )

        # The ambient mesh makes sp/pp kernels (nested shard_maps inside
        # the model) resolve their axes at trace time.
        def with_mesh(fn):
            @functools.wraps(fn)
            def run(*a, **kw):
                with _set_mesh(mesh):
                    return fn(*a, **kw)

            return run

        wrapped_init = with_mesh(init_jit)
        wrapped_step = with_mesh(step_jit)
        # The raw (untraced) step lets callers embed the step in a larger
        # jit — e.g. a lax.scan over K steps — without nesting pjit
        # inside jit, which compiles far slower than tracing the body
        # directly (bench.py's scan loop uses this).
        wrapped_step.raw = train_step
        wrapped_step.shardings = (ssh,) + bsh
        return wrapped_init, wrapped_step, ssh

    return build


def _accepts_deterministic(model: nn.Module) -> bool:
    import inspect

    call = getattr(model, "__call__", None)
    if call is None:
        return False
    try:
        return "deterministic" in inspect.signature(call).parameters
    except (TypeError, ValueError):  # pragma: no cover
        return False
