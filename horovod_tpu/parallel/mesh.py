"""Device-mesh construction and topology discovery.

This replaces the reference's rank-topology machinery (MPI comm splits into
global/local/cross communicators, ref: horovod/common/mpi/mpi_context.cc and
controller.h:172-188) with the TPU-native equivalent: a `jax.sharding.Mesh`
over the slice's chips, built so that bandwidth-hungry axes ride ICI and
only the outermost axis crosses DCN (multi-slice / multi-host boundaries).

Axis convention (outer → inner):
    pp   pipeline stages        (cheapest comms: p2p activations, DCN-safe)
    dp   data parallel          (gradient reduce-scatter/all-reduce)
    ep   expert parallel        (MoE all-to-all token dispatch)
    sp   sequence/context par.  (ring-attention ppermute / Ulysses all-to-all)
    tp   tensor parallel        (per-layer all-reduce — needs fattest ICI)

The reference's LOCAL/CROSS communicators map to "devices on my host" /
"my device-index across hosts"; helpers below expose the same notions.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order, outer (slow, DCN-tolerant) → inner (fast ICI).
AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")

# The single data-parallel axis used by the horovod-style API
# (hvd.allreduce inside jit reduces over this axis).
HVD_AXIS = "hvd"


def _factor_devices(n: int, requested: Dict[str, int]) -> Dict[str, int]:
    """Fill in -1 entries so the product of axis sizes equals n."""
    sizes = dict(requested)
    known = 1
    free = [a for a, s in sizes.items() if s == -1]
    for a, s in sizes.items():
        if s != -1:
            known *= s
    if n % known != 0:
        raise ValueError(
            f"mesh axes {sizes} do not divide device count {n}"
        )
    rest = n // known
    if not free:
        if known != n:
            raise ValueError(f"mesh axes {sizes} do not cover device count {n}")
        return sizes
    if len(free) == 1:
        sizes[free[0]] = rest
        return sizes
    raise ValueError("at most one axis size may be -1")


def create_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
    allow_split_physical_axes: bool = True,
) -> Mesh:
    """Build a Mesh whose axis order follows AXIS_ORDER (unknown axes keep
    their given order after the known ones). Uses jax's topology-aware
    device-mesh builders so inner axes land on contiguous ICI neighbors."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {HVD_AXIS: n}
    axis_sizes = _factor_devices(n, dict(axis_sizes))

    names = sorted(
        axis_sizes.keys(),
        key=lambda a: AXIS_ORDER.index(a) if a in AXIS_ORDER else len(AXIS_ORDER),
    )
    shape = tuple(axis_sizes[a] for a in names)

    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(names))


def create_hybrid_mesh(
    ici_axis_sizes: Dict[str, int],
    dcn_axis_sizes: Dict[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Multi-slice mesh: `dcn_axis_sizes` axes cross the slow DCN network,
    `ici_axis_sizes` stay within a slice's ICI torus. This is the TPU
    equivalent of the reference's hierarchical allreduce split
    (ref: nccl_operations.cc:190-405 — intra-node NCCL + cross-node MPI)."""
    devices = list(devices if devices is not None else jax.devices())
    names = sorted(
        list(ici_axis_sizes) + list(dcn_axis_sizes),
        key=lambda a: AXIS_ORDER.index(a) if a in AXIS_ORDER else len(AXIS_ORDER),
    )
    try:
        from jax.experimental import mesh_utils

        mesh_shape = [ici_axis_sizes.get(a, 1) for a in names]
        dcn_shape = [dcn_axis_sizes.get(a, 1) for a in names]
        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape, dcn_shape, devices=devices
        )
        return Mesh(dev_array, axis_names=tuple(names))
    except Exception:
        merged = {a: ici_axis_sizes.get(a, 1) * dcn_axis_sizes.get(a, 1) for a in names}
        return create_mesh(merged, devices)


def data_parallel_mesh(devices: Optional[Sequence] = None, axis_name: str = HVD_AXIS) -> Mesh:
    """1-D mesh over all chips — the horovod-equivalent world communicator."""
    return create_mesh({axis_name: -1}, devices)


def local_device_count() -> int:
    return jax.local_device_count()


def process_topology() -> Tuple[int, int, int, int]:
    """(rank, size, local_rank, local_size) in the multi-controller sense.

    On a TPU pod each jax process owns local_device_count() chips; the
    reference's notion of one-rank-per-accelerator maps to one-process-
    per-host here, with chips addressed through the mesh."""
    return (
        jax.process_index(),
        jax.process_count(),
        0,
        jax.local_device_count(),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *axes: str) -> NamedSharding:
    """Shard the leading (batch) dim over the given mesh axes."""
    use = tuple(a for a in axes if a in mesh.axis_names) or None
    return NamedSharding(mesh, P(use))
