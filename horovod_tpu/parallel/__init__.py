"""Parallelism strategies over TPU device meshes.

The reference implements data parallelism only (SURVEY.md §2.6); this
layer adds the mesh-axis strategies a TPU framework needs first-class:
tensor parallel (tp), pipeline parallel (pp), sequence/context parallel
(sp: ring attention + Ulysses all-to-all), and expert parallel (ep: MoE
all-to-all dispatch), all composable on one `jax.sharding.Mesh`.
"""
from . import mesh
from .mesh import create_mesh, create_hybrid_mesh, data_parallel_mesh, AXIS_ORDER, HVD_AXIS
from .sharding import DEFAULT_RULES, FSDP_RULES, batch_spec, init_sharded, logical_sharding
from .step import wrap_step
from .train import TrainState, lm_loss, make_train_step, softmax_xent
