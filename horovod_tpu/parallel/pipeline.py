"""Pipeline parallelism: GPipe schedule over the pp mesh axis.

A capability the reference lacks (SURVEY.md §2.6: PP absent). TPU-native
construction: `shard_map` manualizes ONLY the pp axis (dp/tp/sp/ep stay
under GSPMD inside each stage), layer-stacked parameters are sharded
over pp on their stage dim, and activations flow stage-to-stage with
`lax.ppermute` — neighbor ICI hops on the torus. The schedule is the
classic GPipe fill/drain: T = M + S - 1 ticks for M microbatches over S
stages, bubble fraction (S-1)/(M+S-1). Fully differentiable, so one
jitted train step backprops through the whole pipeline.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import axis_index as _axis_index, shard_map


from ..utils.compat import pvary as _pvary


def gpipe(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    num_microbatches: Optional[int] = None,
):
    """Run `x` through S pipeline stages.

    stage_fn(params_slice, act) -> act: applies one stage's layers; must
      preserve the activation shape.
    stage_params: pytree whose leaves have a leading stage dim of size S
      (= mesh.shape[axis]), sharded over `axis`.
    x: full batch (B, ...); B must divide into `num_microbatches`
      (default S) microbatches.

    Returns the full-batch output with x's shape.
    """
    S = mesh.shape[axis]
    if S == 1:
        return stage_fn(jax.tree.map(lambda a: a[0], stage_params), x)
    M = num_microbatches or S
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    act_dtype = x.dtype
    # The shard_map boundary stays f32 in both directions: the cross-pp
    # all-reduces it implies (output psum; backward, the transpose of the
    # input pvary) must not be low-precision — bf16 all-reduce inside a
    # partial-manual region crashes XLA CPU's AllReducePromotion pass
    # (observed jax 0.9), and f32 summation is numerically safer anyway.
    xs = x.reshape(M, mb, *x.shape[1:]).astype(jnp.float32)

    def worker(params_local, xs):
        # params_local leading stage dim is 1 locally.
        p = jax.tree.map(lambda a: jnp.squeeze(a, 0), params_local)
        stage = _axis_index(axis)
        # Mark pp-varying up front: carries become varying inside the
        # loop (ppermute / per-stage masks) and the explicit pvary pins
        # the backward psum of xs at f32.
        xs = _pvary(xs, axis)
        state = _pvary(jnp.zeros(xs.shape[1:], act_dtype), axis)
        outs = _pvary(jnp.zeros(xs.shape, jnp.float32), axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outs = carry
            # Stage 0 injects microbatch t (clamped during drain).
            inject = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False
            ).astype(act_dtype)
            cur = jnp.where(stage == 0, inject, state)
            y = stage_fn(p, cur)
            # Last stage banks microbatch t-(S-1) (clamped during fill;
            # the mask kills out-of-range writes).
            oi = t - (S - 1)
            banked = jax.lax.dynamic_update_index_in_dim(
                outs, y.astype(jnp.float32), jnp.clip(oi, 0, M - 1), 0
            )
            keep = (stage == S - 1) & (oi >= 0)
            outs = jnp.where(keep, banked, outs)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, outs), None

        (state, outs), _ = jax.lax.scan(
            tick, (state, outs), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs; psum over the masked
        # buffers replicates them to every stage (outs elsewhere are 0).
        masked = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(masked, axis)

    pspec = jax.tree.map(lambda _: P(axis), stage_params)
    out = shard_map(
        worker,
        mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        axis_names={axis},
        check_rep=True,
    )(stage_params, xs)
    return out.reshape(B, *x.shape[1:]).astype(act_dtype)


def stack_stage_params(layer_params: Any, n_stages: int) -> Any:
    """Reshape layer-stacked params (leading dim L) into stage-stacked
    params (leading dims (S, L/S) collapsed to S with L/S layers inside):
    returns a tree with leading dims (S, L/S, ...)."""
    def reshape(a):
        L = a.shape[0]
        if L % n_stages != 0:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])

    return jax.tree.map(reshape, layer_params)
