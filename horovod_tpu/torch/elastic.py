"""Elastic state for torch models
(ref: horovod/torch/elastic.py:51-84 TorchState).

In-memory deepcopy save/restore + rank-0 broadcast sync of model and
optimizer state_dicts, composing with the shared ObjectState for scalar
attributes (epoch/batch), per the reference's contract.
"""
from __future__ import annotations

import copy

from ..elastic.state import ObjectState


class TorchState(ObjectState):
    """(ref: torch/elastic.py:51-84)"""

    def __init__(self, model=None, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer
        self._saved_model_state = None
        self._saved_opt_state = None
        super().__init__(**kwargs)

    def save(self):
        if self.model is not None:
            self._saved_model_state = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._saved_opt_state = copy.deepcopy(self.optimizer.state_dict())
        super().save()

    def restore(self):
        if self.model is not None and self._saved_model_state is not None:
            self.model.load_state_dict(self._saved_model_state)
        if self.optimizer is not None and self._saved_opt_state is not None:
            self.optimizer.load_state_dict(self._saved_opt_state)
        super().restore()

    def sync(self):
        from . import broadcast_object, broadcast_parameters

        if self.model is not None:
            broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            from . import broadcast_optimizer_state

            broadcast_optimizer_state(self.optimizer, root_rank=0)
        super().sync()
