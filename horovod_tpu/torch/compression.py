"""Gradient compression for the torch adapter
(ref: horovod/torch/compression.py — fp16 on-the-wire compression).

Thin re-export of the single-source interface in
`common/compression.py` plus the torch tensor-type adapter — see
`ops/compression.py` for the layering note (framework compressors vs
the data-plane wire codecs)."""
from __future__ import annotations

from ..common.compression import Compressor, NoneCompressor

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor",
           "Compression"]


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        import torch

        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
