"""Gradient compression for the torch adapter
(ref: horovod/torch/compression.py — fp16 on-the-wire compression)."""
from __future__ import annotations


class NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    @staticmethod
    def compress(tensor):
        import torch

        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
