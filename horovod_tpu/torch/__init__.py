"""`horovod_tpu.torch` — drop-in surface of `horovod.torch` for PyTorch
users (ref: horovod/torch/mpi_ops.py, horovod/torch/optimizer.py,
horovod/torch/functions.py).

    import horovod_tpu.torch as hvd
    hvd.init()
    optimizer = hvd.DistributedOptimizer(optimizer,
                                         named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

Tensors ride the same asynchronous name-negotiated engine as the JAX
eager path (numpy bridge, zero-copy where torch memory is contiguous);
on TPU hardware the JAX path is the performance surface — this adapter
exists for capability parity and CPU-cluster jobs.
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from ..common.basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    metrics,
    mpi_built,
    gloo_built,
    nccl_built,
    ccl_built,
    check_extension,
    check_num_rank_power_of_2,
    cuda_built,
    ddl_built,
    gloo_enabled,
    gpu_available,
    mpi_enabled,
    mpi_threads_supported,
    num_rank_is_power_2,
    rocm_built,
    rank,
    shutdown,
    size,
)
from ..common import basics as _basics
from ..common.exceptions import HorovodInternalError
from ..common.types import Adasum, Average, ReduceOp, Sum  # noqa: F401
from . import compression as _compression_mod
from .compression import Compression  # noqa: F401
from .optimizer import DistributedOptimizer  # noqa: F401
from .sync_batch_norm import SyncBatchNorm  # noqa: F401
from .elastic import TorchState  # noqa: F401


def _to_numpy(tensor) -> np.ndarray:
    return tensor.detach().cpu().numpy()


def _from_numpy(arr: np.ndarray, like):
    import torch

    return torch.from_numpy(np.ascontiguousarray(arr)).to(
        dtype=like.dtype, device=like.device
    )


def _engine():
    eng = _basics.engine()
    if eng is None:
        raise HorovodInternalError(
            "horovod_tpu.torch collectives need process mode (hvdrun) or "
            "size()==1"
        )
    return eng


def _resolve_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    if op is not None and average is not None:
        raise ValueError("specify op= or the legacy average=, not both")
    if op is None:
        return ReduceOp.AVERAGE if (average is None or average) else ReduceOp.SUM
    return op


# ---------------------------------------------------------------------------
# Async handle API (ref: horovod/torch/mpi_ops.py:83-219)
_handles = {}

# Single-process immediate results (negative handles): the reference
# works without horovodrun at size 1, so the async API must too — there
# is no engine to enqueue into, the "collective" result is computed on
# the spot (ref: a size-1 MPI world completes ops locally).
from ..common.async_handles import LocalResultStore

_local_results = LocalResultStore()


def _local_handle(result) -> int:
    return _local_results.put(result)


def _single() -> bool:
    return _basics.engine() is None and _basics.size() == 1


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0) -> int:
    rop = _resolve_op(op, average)
    if _single():
        arr = _to_numpy(tensor) * prescale_factor * postscale_factor
        h = _local_handle(arr)
    else:
        h = _engine().enqueue_allreduce(
            _to_numpy(tensor), name=name, op=rop,
            prescale=prescale_factor, postscale=postscale_factor,
        )
    _handles[h] = ("allreduce", tensor, None)
    return h


def allreduce_async_(tensor, average=None, name=None, op=None,
                     prescale_factor=1.0, postscale_factor=1.0) -> int:
    """In-place variant (ref: mpi_ops.py allreduce_async_)."""
    h = allreduce_async(tensor, average, name, op, prescale_factor,
                        postscale_factor)
    _handles[h] = ("allreduce_", tensor, None)
    return h


def allgather_async(tensor, name=None) -> int:
    if _single():
        h = _local_handle(_to_numpy(tensor))
    else:
        h = _engine().enqueue_allgather(_to_numpy(tensor), name=name)
    _handles[h] = ("allgather", tensor, None)
    return h


def broadcast_async(tensor, root_rank, name=None) -> int:
    if _single():
        h = _local_handle(_to_numpy(tensor))
    else:
        h = _engine().enqueue_broadcast(_to_numpy(tensor), root_rank,
                                        name=name)
    _handles[h] = ("broadcast", tensor, None)
    return h


def broadcast_async_(tensor, root_rank, name=None) -> int:
    h = broadcast_async(tensor, root_rank, name)
    _handles[h] = ("broadcast_", tensor, None)
    return h


def alltoall_async(tensor, splits=None, name=None) -> int:
    if _single():
        arr = np.array(_to_numpy(tensor))
        rows = arr.shape[0] if arr.ndim else 1
        if splits is not None:
            # Same validation the engine applies (enqueue_alltoall):
            # buggy splits must not pass locally and fail under hvdrun.
            sp = [int(x) for x in splits]
            if sum(sp) != rows:
                raise ValueError(
                    f"splits sum {sum(sp)} != first dim {rows}")
        else:
            sp = [rows]
        h = _local_handle((arr, sp))
    else:
        h = _engine().enqueue_alltoall(
            _to_numpy(tensor), list(splits) if splits is not None else None,
            name=name,
        )
    _handles[h] = ("alltoall", tensor, None)
    return h


def poll(handle: int) -> bool:
    if handle in _local_results:
        return True
    if handle < 0:
        # A consumed/unknown local handle: engine mode returns False
        # here, so single-process mode must too.
        return False
    return _engine().poll(handle)


def synchronize(handle: int):
    """(ref: mpi_ops.py synchronize — returns the op's result; in-place
    ops copy into the original tensor.)"""
    kind, tensor, _ = _handles.pop(handle, (None, None, None))
    if handle in _local_results:
        out = _local_results.pop(handle)
    elif handle < 0:
        # Negative handles never reach the engine; falling through
        # would surface as an opaque engine KeyError.
        raise ValueError(
            f"handle {handle} was already synchronized (results are "
            "consumed on first synchronize)"
        )
    else:
        out = _engine().synchronize(handle)
    if kind == "alltoall":
        arr, recv_splits = out
        import torch

        return _from_numpy(arr, tensor), torch.tensor(recv_splits)
    if kind in ("allreduce_", "broadcast_"):
        result = _from_numpy(np.asarray(out), tensor)
        tensor.copy_(result.reshape(tensor.shape))
        return tensor
    if kind is None:
        return out
    return _from_numpy(np.asarray(out), tensor)


# ---------------------------------------------------------------------------
# Synchronous ops
def _sync_single(tensor, op: ReduceOp, prescale, postscale):
    # size-1 fast path shared by all sync ops.
    arr = _to_numpy(tensor)
    if op == ReduceOp.SUM:
        arr = arr * _basics.size()
    return _from_numpy(arr * prescale * postscale, tensor).reshape(tensor.shape)


def _allreduce_impl(tensor, name, rop, prescale_factor, postscale_factor):
    if _basics.size() == 1:
        return _sync_single(tensor, rop, prescale_factor, postscale_factor)
    return synchronize(
        allreduce_async(tensor, None, name, rop, prescale_factor,
                        postscale_factor)
    )


class _HorovodAllreduce:
    """Autograd bridge: backward of allreduce is allreduce of the
    cotangent with the same op (ref: torch/mpi_ops.py:161-177
    HorovodAllreduce autograd Function)."""

    _cls = None

    @classmethod
    def get(cls):
        if cls._cls is None:
            import torch

            class F(torch.autograd.Function):
                @staticmethod
                def forward(ctx, tensor, name, rop, pre, post):
                    ctx.hvd_args = (name, rop, pre, post)
                    return _allreduce_impl(tensor, name, rop, pre, post)

                @staticmethod
                def backward(ctx, grad_output):
                    name, rop, pre, post = ctx.hvd_args
                    g = _allreduce_impl(
                        grad_output.contiguous(),
                        f"{name}.grad" if name else None, rop, pre, post,
                    )
                    return g, None, None, None, None

            cls._cls = F
        return cls._cls


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0):
    rop = _resolve_op(op, average)
    if getattr(tensor, "requires_grad", False):
        return _HorovodAllreduce.get().apply(
            tensor, name, rop, prescale_factor, postscale_factor
        )
    return _allreduce_impl(tensor, name, rop, prescale_factor,
                           postscale_factor)


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0):
    rop = _resolve_op(op, average)
    if _basics.size() == 1:
        tensor.copy_(_sync_single(tensor, rop, prescale_factor,
                                  postscale_factor))
        return tensor
    return synchronize(
        allreduce_async_(tensor, None, name, rop, prescale_factor,
                         postscale_factor)
    )


def grouped_allreduce(tensors, average=None, name=None, op=None):
    rop = _resolve_op(op, average)
    base = name or "grouped"
    handles = [
        allreduce_async(t, None, f"{base}.{i}", rop)
        for i, t in enumerate(tensors)
    ]
    return [synchronize(h) for h in handles]


def allgather(tensor, name=None):
    if _basics.size() == 1:
        return tensor.clone()
    return synchronize(allgather_async(tensor, name))


def broadcast(tensor, root_rank, name=None):
    if _basics.size() == 1:
        return tensor.clone()
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_(tensor, root_rank, name=None):
    if _basics.size() == 1:
        return tensor
    return synchronize(broadcast_async_(tensor, root_rank, name))


def alltoall(tensor, splits=None, name=None):
    if _basics.size() == 1:
        import torch

        s = splits if splits is not None else [tensor.shape[0]]
        return tensor.clone(), torch.tensor(list(s))
    return synchronize(alltoall_async(tensor, splits, name))


def join() -> int:
    from ..ops import join as _join

    return _join()


def barrier():
    from ..ops import barrier as _barrier

    _barrier()


# ---------------------------------------------------------------------------
# State broadcast helpers (ref: horovod/torch/functions.py:30-227)
def broadcast_parameters(params, root_rank: int = 0):
    """In-place broadcast of a state_dict or named_parameters iterable."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None:
            continue
        try:
            handles.append(broadcast_async_(p, root_rank, name=f"bp.{name}"))
        except AttributeError:
            continue  # non-tensor entries
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0):
    """(ref: functions.py:60-107) — broadcast optimizer state tensors;
    scalar hyper-state rides broadcast_object."""
    import torch

    state = optimizer.state_dict()
    # Tensors in state broadcast in place; the rest via object broadcast.
    scalars = broadcast_object(
        {
            k: v for k, v in state.items() if k != "state"
        },
        root_rank=root_rank, name="opt_meta",
    )
    state.update(scalars)
    for pid, pstate in sorted(state.get("state", {}).items()):
        for key, val in sorted(pstate.items()):
            if isinstance(val, torch.Tensor):
                broadcast_(val, root_rank, name=f"opt.{pid}.{key}")
            else:
                pstate[key] = broadcast_object(
                    val, root_rank, name=f"opt.{pid}.{key}"
                )
    optimizer.load_state_dict(state)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    from ..common.functions import broadcast_object as _bo

    return _bo(obj, root_rank=root_rank, name=name)


def allgather_object(obj, name: Optional[str] = None):
    from ..common.functions import allgather_object as _ao

    return _ao(obj, name=name)
