"""DistributedOptimizer for torch — hook-fired async allreduce of grads
with synchronization in step() (ref: horovod/torch/optimizer.py:32-207,
factory at :337-414).
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

from ..common import basics as _basics
from ..common.types import ReduceOp
from .compression import Compression


class _DistributedOptimizer:
    """Proxy wrapping a torch.optim.Optimizer. Gradients are allreduced
    asynchronously as they become ready (post-accumulate-grad hooks, the
    engine overlapping communication with the rest of backward — the
    reference's core trick) and joined in step()."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 op: ReduceOp = ReduceOp.AVERAGE,
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self._prescale = prescale_factor
        self._postscale = postscale_factor
        self.backward_passes_per_step = backward_passes_per_step
        self._passes = 0
        self._handles = {}      # param -> (handle, ctx)
        self._hook_handles = []
        self._synchronized = False
        self._should_synchronize = True

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [
                (f"param.{gi}.{pi}", p)
                for gi, group in enumerate(optimizer.param_groups)
                for pi, p in enumerate(group["params"])
            ]
        # Duplicate-name check (ref: optimizer.py:52-64).
        names = [n for n, _ in named]
        if len(set(names)) != len(names):
            raise ValueError("parameter names must be unique")
        self._names = {p: n for n, p in named}
        if _basics.size() > 1:
            self._register_hooks(p for _, p in named)

    # -- attribute proxying ------------------------------------------------
    def __getattr__(self, item):
        return getattr(self._opt, item)

    @property
    def param_groups(self):
        return self._opt.param_groups

    @property
    def state(self):
        return self._opt.state

    # ----------------------------------------------------------------------
    def _register_hooks(self, params):
        for p in params:
            if not p.requires_grad:
                continue
            if hasattr(p, "register_post_accumulate_grad_hook"):
                h = p.register_post_accumulate_grad_hook(self._make_hook(p))
                self._hook_handles.append(h)

    def _make_hook(self, p):
        def hook(*ignore):
            self._passes_check_and_reduce(p)

        return hook

    def _passes_check_and_reduce(self, p):
        # Local accumulation: only communicate on the boundary pass
        # (ref: optimizer.py backward_passes_per_step).
        if (self._passes + 1) % self.backward_passes_per_step != 0:
            return
        if p in self._handles or p.grad is None:
            return
        self._handles[p] = self._allreduce_grad_async(p)

    def _allreduce_grad_async(self, p):
        import horovod_tpu.torch as hvd_torch

        tensor, ctx = self._compression.compress(p.grad)
        # Accumulated local passes are NOT rescaled by 1/k — matching the
        # reference: backward_passes_per_step grows the effective batch
        # (ref: optimizer.py backward_passes_per_step docs).
        handle = hvd_torch.allreduce_async(
            tensor, name=f"grad.{self._names[p]}", op=self._op,
            prescale_factor=self._prescale,
            postscale_factor=self._postscale,
        )
        return handle, ctx

    def synchronize(self):
        """Join all outstanding grad allreduces
        (ref: optimizer.py:151-200)."""
        import horovod_tpu.torch as hvd_torch

        if _basics.size() > 1:
            missing = [
                p for p in self._names
                if p.requires_grad and p.grad is not None
                and p not in self._handles
            ]
            for p in missing:
                self._handles[p] = self._allreduce_grad_async(p)
            for p, (handle, ctx) in list(self._handles.items()):
                out = hvd_torch.synchronize(handle)
                p.grad.copy_(
                    self._compression.decompress(out, ctx).reshape(
                        p.grad.shape
                    )
                )
        self._handles.clear()
        self._synchronized = True

    from contextlib import contextmanager

    @contextmanager
    def skip_synchronize(self):
        """For manual synchronize() + grad clipping before step()
        (ref: optimizer.py skip_synchronize)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        self._passes += 1
        boundary = self._passes % self.backward_passes_per_step == 0
        if boundary and self._should_synchronize and not self._synchronized:
            self.synchronize()
        self._synchronized = False
        if not boundary:
            return None
        return self._opt.step(closure)

    def zero_grad(self, *a, **kw):
        if self._passes % self.backward_passes_per_step != 0:
            # Keep accumulating locally between boundaries.
            return None
        return self._opt.zero_grad(*a, **kw)

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, sd):
        return self._opt.load_state_dict(sd)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = ReduceOp.AVERAGE,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0):
    """(ref: horovod/torch/optimizer.py:337-414)"""
    return _DistributedOptimizer(
        optimizer, named_parameters, compression, backward_passes_per_step,
        op, prescale_factor, postscale_factor,
    )
