"""DistributedOptimizer for torch — hook-fired async allreduce of grads
with synchronization in step() (ref: horovod/torch/optimizer.py:32-207,
factory at :337-414).

The wrapper is a dynamic subclass of the wrapped optimizer's own class
(the reference's pattern, ref: optimizer.py:337-356), so
`isinstance(opt, torch.optim.Optimizer)` holds and
`torch.optim.lr_scheduler` accepts it. It aliases the wrapped
instance's state (shared __dict__), overriding step/zero_grad and
adding synchronize/skip_synchronize.
"""
from __future__ import annotations

from contextlib import contextmanager

from ..common import basics as _basics
from ..common.types import ReduceOp
from .compression import Compression


class _DistributedMixin:
    """Methods grafted onto the dynamic subclass."""

    def _hvd_init(self, optimizer, named_parameters, compression,
                  backward_passes_per_step, op, prescale_factor,
                  postscale_factor):
        object.__setattr__(self, "__dict__", optimizer.__dict__)
        self._hvd_opt_cls = type(optimizer)
        self._compression = compression
        self._op = op
        self._prescale = prescale_factor
        self._postscale = postscale_factor
        self.backward_passes_per_step = backward_passes_per_step
        self._passes = 0
        self._handles = {}      # param -> (handle, ctx)
        self._hook_handles = []
        self._synchronized = False
        self._should_synchronize = True

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [
                (f"param.{gi}.{pi}", p)
                for gi, group in enumerate(optimizer.param_groups)
                for pi, p in enumerate(group["params"])
            ]
        # Duplicate-name check (ref: optimizer.py:52-64).
        names = [n for n, _ in named]
        if len(set(names)) != len(names):
            raise ValueError("parameter names must be unique")
        self._names = {p: n for n, p in named}
        if _basics.size() > 1:
            self._register_hooks(p for _, p in named)

    # ------------------------------------------------------------------
    def _register_hooks(self, params):
        for p in params:
            if not p.requires_grad:
                continue
            if hasattr(p, "register_post_accumulate_grad_hook"):
                h = p.register_post_accumulate_grad_hook(self._make_hook(p))
                self._hook_handles.append(h)

    def _make_hook(self, p):
        def hook(*ignore):
            self._passes_check_and_reduce(p)

        return hook

    def _passes_check_and_reduce(self, p):
        # Local accumulation: only communicate on the boundary pass
        # (ref: optimizer.py backward_passes_per_step).
        if (self._passes + 1) % self.backward_passes_per_step != 0:
            return
        if p in self._handles or p.grad is None:
            return
        self._handles[p] = self._allreduce_grad_async(p)

    def _allreduce_grad_async(self, p):
        import horovod_tpu.torch as hvd_torch

        tensor, ctx = self._compression.compress(p.grad)
        # Accumulated local passes are NOT rescaled by 1/k — matching the
        # reference: backward_passes_per_step grows the effective batch
        # (ref: optimizer.py backward_passes_per_step docs).
        handle = hvd_torch.allreduce_async(
            tensor, name=f"grad.{self._names[p]}", op=self._op,
            prescale_factor=self._prescale,
            postscale_factor=self._postscale,
        )
        return handle, ctx

    def synchronize(self):
        """Join all outstanding grad allreduces
        (ref: optimizer.py:151-200)."""
        import horovod_tpu.torch as hvd_torch

        if _basics.size() > 1:
            missing = [
                p for p in self._names
                if p.requires_grad and p.grad is not None
                and p not in self._handles
            ]
            for p in missing:
                self._handles[p] = self._allreduce_grad_async(p)
            for p, (handle, ctx) in list(self._handles.items()):
                out = hvd_torch.synchronize(handle)
                p.grad.copy_(
                    self._compression.decompress(out, ctx).reshape(
                        p.grad.shape
                    )
                )
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """For manual synchronize() + grad clipping before step()
        (ref: optimizer.py skip_synchronize)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        self._passes += 1
        boundary = self._passes % self.backward_passes_per_step == 0
        if boundary and self._should_synchronize and not self._synchronized:
            self.synchronize()
        self._synchronized = False
        if not boundary:
            return None
        return self._hvd_opt_cls.step(self, closure)

    def zero_grad(self, *a, **kw):
        if self._passes % self.backward_passes_per_step != 0:
            # Keep accumulating locally between boundaries.
            return None
        return self._hvd_opt_cls.zero_grad(self, *a, **kw)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = ReduceOp.AVERAGE,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0):
    """(ref: horovod/torch/optimizer.py:337-414)"""
    base_cls = type(optimizer)
    members = {
        k: v for k, v in vars(_DistributedMixin).items()
        if not k.startswith("__")
    }
    cls = type(f"Distributed{base_cls.__name__}", (base_cls,), members)

    inst = cls.__new__(cls)
    inst._hvd_init(optimizer, named_parameters, compression,
                   backward_passes_per_step, op, prescale_factor,
                   postscale_factor)
    return inst
