"""DistributedOptimizer for torch — hook-fired async allreduce of grads
with synchronization in step() (ref: horovod/torch/optimizer.py:32-207,
factory at :337-414).

The wrapper is a dynamic subclass of the wrapped optimizer's own class
(the reference's pattern, ref: optimizer.py:337-356), so
`isinstance(opt, torch.optim.Optimizer)` holds and
`torch.optim.lr_scheduler` accepts it. It aliases the wrapped
instance's state (shared __dict__), overriding step/zero_grad and
adding synchronize/skip_synchronize.
"""
from __future__ import annotations

from contextlib import contextmanager

from ..common import basics as _basics
from ..common.types import ReduceOp
from .compression import Compression


class _DistributedMixin:
    """Methods grafted onto the dynamic subclass."""

    def _hvd_init(self, optimizer, named_parameters, compression,
                  backward_passes_per_step, op, prescale_factor,
                  postscale_factor):
        object.__setattr__(self, "__dict__", optimizer.__dict__)
        self._hvd_opt_cls = type(optimizer)
        self._compression = compression
        self._op = op
        self._prescale = prescale_factor
        self._postscale = postscale_factor
        self.backward_passes_per_step = backward_passes_per_step
        self._passes = 0
        self._handles = {}      # param -> (handle, ctx)
        self._hook_handles = []
        self._synchronized = False
        self._should_synchronize = True

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [
                (f"param.{gi}.{pi}", p)
                for gi, group in enumerate(optimizer.param_groups)
                for pi, p in enumerate(group["params"])
            ]
        # Duplicate-name check (ref: optimizer.py:52-64).
        names = [n for n, _ in named]
        if len(set(names)) != len(names):
            raise ValueError("parameter names must be unique")
        self._names = {p: n for n, p in named}
        if _basics.size() > 1:
            self._register_hooks(p for _, p in named)

    # ------------------------------------------------------------------
    def _register_hooks(self, params):
        for p in params:
            if not p.requires_grad:
                continue
            if hasattr(p, "register_post_accumulate_grad_hook"):
                h = p.register_post_accumulate_grad_hook(self._make_hook(p))
                self._hook_handles.append(h)

    def _make_hook(self, p):
        def hook(*ignore):
            self._passes_check_and_reduce(p)

        return hook

    def _passes_check_and_reduce(self, p):
        # Local accumulation: only communicate on the boundary pass
        # (ref: optimizer.py backward_passes_per_step).
        if (self._passes + 1) % self.backward_passes_per_step != 0:
            return
        if p in self._handles or p.grad is None:
            return
        self._handles[p] = self._allreduce_grad_async(p)

    def _allreduce_grad_async(self, p):
        import horovod_tpu.torch as hvd_torch

        tensor, ctx = self._compression.compress(p.grad)
        # Accumulated local passes are NOT rescaled by 1/k — matching the
        # reference: backward_passes_per_step grows the effective batch
        # (ref: optimizer.py backward_passes_per_step docs).
        handle = hvd_torch.allreduce_async(
            tensor, name=f"grad.{self._names[p]}", op=self._op,
            prescale_factor=self._prescale,
            postscale_factor=self._postscale,
        )
        return handle, ctx

    def synchronize(self):
        """Join all outstanding grad allreduces
        (ref: optimizer.py:151-200)."""
        import horovod_tpu.torch as hvd_torch

        if _basics.size() > 1:
            missing = [
                p for p in self._names
                if p.requires_grad and p.grad is not None
                and p not in self._handles
            ]
            for p in missing:
                self._handles[p] = self._allreduce_grad_async(p)
            for p, (handle, ctx) in list(self._handles.items()):
                out = hvd_torch.synchronize(handle)
                p.grad.copy_(
                    self._compression.decompress(out, ctx).reshape(
                        p.grad.shape
                    )
                )
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """For manual synchronize() + grad clipping before step()
        (ref: optimizer.py skip_synchronize)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        self._passes += 1
        boundary = self._passes % self.backward_passes_per_step == 0
        if boundary and self._should_synchronize and not self._synchronized:
            self.synchronize()
        self._synchronized = False
        if not boundary:
            return None
        return self._hvd_opt_cls.step(self, closure)

    def zero_grad(self, *a, **kw):
        if self._passes % self.backward_passes_per_step != 0:
            # Keep accumulating locally between boundaries.
            return None
        return self._hvd_opt_cls.zero_grad(self, *a, **kw)


class _AdasumDeltaMixin(_DistributedMixin):
    """Delta-model Adasum optimizer (ref: horovod/torch/optimizer.py:210-321
    _DistributedAdasumOptimizer).

    `DistributedOptimizer(op=Adasum)` is NOT a gradient allreduce in the
    reference: each rank applies its *local* optimizer step, and the
    resulting weight **deltas** are Adasum-combined:

        start = current.copy()
        step()                      # current = start - alpha*f(g_local)
        delta = current - start     # the local model movement
        delta = adasum(delta)       # scale-insensitive VHDD combine
        current = start + delta

    The hook-fired variant below mirrors the reference's per-parameter
    pipelining: when a parameter's gradient is ready (on the boundary
    pass), the local step runs for just that parameter, the delta is
    launched asynchronously, and step() joins + applies start+delta.
    With a linear optimizer (plain SGD) this coincides with gradient
    Adasum because VHDD is degree-1 homogeneous; with momentum/Adam the
    trajectories genuinely differ — which is why the reference
    dispatches to a separate class rather than reusing the grad path.
    """

    def _hvd_init(self, optimizer, named_parameters, compression,
                  backward_passes_per_step, op, prescale_factor,
                  postscale_factor):
        import torch

        # Explicit base call: the dynamic Distributed<X> class copies
        # these methods into its own dict, so zero-arg super() would
        # not resolve against this mixin.
        _DistributedMixin._hvd_init(
            self, optimizer, named_parameters, compression,
            backward_passes_per_step, op, prescale_factor,
            postscale_factor)
        # Placeholder starts; populated right before each local step
        # (ref: optimizer.py:255-258).
        self._starting = {
            p: torch.zeros_like(p, requires_grad=False)
            for p in self._names
        }

    def _allreduce_grad_async(self, p):
        """Local step on just `p`, then launch the delta Adasum
        (ref: optimizer.py:278-321 _allreduce_grad_async)."""
        import horovod_tpu.torch as hvd_torch

        start = self._starting[p]
        stashed = []
        for group in self.param_groups:
            stashed.append(group["params"])
            group["params"] = [p] if any(p is v for v in group["params"]) \
                else []
        try:
            start.data.copy_(p.data)
            self._hvd_opt_cls.step(self)
            # p now holds the local delta (reuses p's memory, like the
            # reference's p.data.sub_(start)).
            p.data.sub_(start.data)
            tensor, ctx = self._compression.compress(p.data)
            handle = hvd_torch.allreduce_async(
                tensor, name=f"delta.{self._names[p]}",
                op=ReduceOp.ADASUM,
            )
        finally:
            for st, group in zip(stashed, self.param_groups):
                group["params"] = st
        return handle, ctx

    def synchronize(self):
        # The join happens in step(); nothing to do here
        # (ref: optimizer.py:341-342).
        pass

    @contextmanager
    def skip_synchronize(self):
        raise AssertionError(
            "Skipping synchronization is not supported when using "
            "Adasum optimizer."
        )
        yield  # pragma: no cover

    def step(self, closure=None):
        import horovod_tpu.torch as hvd_torch

        loss = closure() if closure is not None else None
        self._passes += 1
        if self._passes % self.backward_passes_per_step != 0:
            return loss
        missing = [
            p for p in self._names
            if p.requires_grad and p.grad is not None
            and p not in self._handles
        ]
        for p in missing:
            self._handles[p] = self._allreduce_grad_async(p)
        for p, (handle, ctx) in list(self._handles.items()):
            out = hvd_torch.synchronize(handle)
            delta = self._compression.decompress(out, ctx).reshape(p.shape)
            start = self._starting[p]
            # start += combined delta; current = start
            # (ref: optimizer.py:364-368).
            start.data.add_(delta)
            p.data.copy_(start.data)
        self._handles.clear()
        return loss


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = ReduceOp.AVERAGE,
                         gradient_predivide_factor: float = 1.0,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0):
    """(ref: horovod/torch/optimizer.py:337-414; Adasum dispatch at
    :437-445 — op=Adasum with >1 rank returns the delta-model
    optimizer, NOT a gradient allreduce).

    ``gradient_predivide_factor`` splits the averaging around the sum
    exactly as the reference does (ref: optimizer.py:428-435 guards,
    :100-111 split): gradients are scaled by 1/f before the sum and
    f/size after it (the engine applies the extra 1/size when lowering
    AVERAGE — see engine.py enqueue_allreduce). Average-only, like the
    reference; the reference's second guard (ROCm) has no TPU analogue.
    ``prescale_factor``/``postscale_factor`` remain exposed as the raw
    mechanics and compose multiplicatively with the split.
    """
    if gradient_predivide_factor != 1.0:
        if op != ReduceOp.AVERAGE:
            raise ValueError(
                "gradient_predivide_factor not supported with op != Average"
            )
        prescale_factor = prescale_factor / gradient_predivide_factor
        postscale_factor = postscale_factor * gradient_predivide_factor
    base_cls = type(optimizer)
    mixin = _DistributedMixin
    if op == ReduceOp.ADASUM and _basics.size() > 1:
        if prescale_factor != 1.0 or postscale_factor != 1.0:
            # The delta path launches the combine without scale factors;
            # silently dropping them would change the effective update
            # (ref: optimizer.py:431-435 predivide is Average-only).
            raise ValueError(
                "prescale_factor/postscale_factor are not supported "
                "with op=Adasum"
            )
        mixin = _AdasumDeltaMixin
    members = {}
    for klass in reversed(mixin.__mro__):
        members.update(
            (k, v) for k, v in vars(klass).items()
            if not k.startswith("__") and klass is not object
        )
    cls = type(f"Distributed{base_cls.__name__}", (base_cls,), members)

    inst = cls.__new__(cls)
    inst._hvd_init(optimizer, named_parameters, compression,
                   backward_passes_per_step, op, prescale_factor,
                   postscale_factor)
    return inst
