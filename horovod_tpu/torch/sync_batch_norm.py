"""Cross-rank synchronized BatchNorm for torch
(ref: horovod/torch/sync_batch_norm.py:30-199 — allreduce of batch
mean/var so every rank normalizes with global statistics).

The reference implements a custom autograd Function with
allgather+allreduce in forward/backward. Here the cross-rank moments
ride the engine's allreduce; gradients flow through the local
normalization (the moment statistics are treated as constants w.r.t.
the graph on other ranks, the standard sync-BN approximation for the
mean/var terms is preserved by autograd on the local contributions).
"""
from __future__ import annotations

import torch


class SyncBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
    """Drop-in replacement for torch.nn.BatchNorm*d in process mode
    (ref: sync_batch_norm.py:30-77)."""

    # Deterministic per-instance id: construction order is identical
    # across ranks (same model code), while id(self) is not — collective
    # names must match cross-rank or negotiation never completes.
    _instances = 0

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._sbn_id = SyncBatchNorm._instances
        SyncBatchNorm._instances += 1

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)"
            )

    def forward(self, input):
        from ..common import basics as _basics

        if (not self.training) or _basics.size() == 1:
            return super().forward(input)

        import horovod_tpu.torch as hvd

        self._check_input_dim(input)
        dims = [0] + list(range(2, input.dim()))
        count = input.numel() // input.shape[1]

        # Global moments via allreduce of E[x], E[x^2] weighted by count
        # (ref: sync_batch_norm.py _SyncBatchNorm forward).
        mean = input.mean(dims)
        sq = (input * input).mean(dims)
        counts = hvd.allreduce(
            torch.tensor([float(count)]), op=hvd.Sum,
            name=f"sbn.{self._sbn_id}.count",
        )
        total = float(counts.item())
        # Differentiable allreduce: gradients flow back through the
        # batch statistics (backward = allreduce of the cotangent), so
        # the -dmu/dx and -dvar/dx terms survive like the reference's
        # custom Function backward (ref: sync_batch_norm.py:80-160).
        g_mean = hvd.allreduce(
            mean * (count / total), op=hvd.Sum,
            name=f"sbn.{self._sbn_id}.mean",
        )
        g_sq = hvd.allreduce(
            sq * (count / total), op=hvd.Sum,
            name=f"sbn.{self._sbn_id}.sq",
        )
        var = g_sq - g_mean * g_mean

        if self.momentum is None:
            momentum = 0.0
        else:
            momentum = self.momentum
        if self.track_running_stats:
            with torch.no_grad():
                unbiased = var * (total / max(total - 1, 1))
                self.running_mean.mul_(1 - momentum).add_(
                    g_mean * momentum
                )
                self.running_var.mul_(1 - momentum).add_(
                    unbiased * momentum
                )
                if self.num_batches_tracked is not None:
                    self.num_batches_tracked += 1

        shape = [1, -1] + [1] * (input.dim() - 2)
        out = (input - g_mean.view(shape)) / torch.sqrt(
            var.view(shape) + self.eps
        )
        if self.affine:
            out = out * self.weight.view(shape) + self.bias.view(shape)
        return out
