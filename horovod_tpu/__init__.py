"""horovod_tpu: a TPU-native distributed deep-learning training framework
with Horovod's capabilities (reference: richardliaw/horovod, read-only at
/root/reference).

    import horovod_tpu as hvd
    hvd.init()
    step = hvd.wrap_step(train_step)        # collectives lower to ICI
    tx = hvd.DistributedOptimizer(optax.adam(1e-3 * hvd.size()))

Public surface mirrors `horovod.torch`/`horovod.tensorflow`
(init/rank/size/allreduce/allgather/broadcast/alltoall/join/
DistributedOptimizer/Compression/elastic/run), re-designed TPU-first:
collectives are XLA ops over a `jax.sharding.Mesh` (ICI/DCN), the async
name-negotiated engine serves the eager path, and the parallel layer
adds mesh-axis parallelism (tp/pp/sp/ep) the reference never had.
"""
from .version import __version__

from .common.basics import (
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    is_homogeneous,
    mesh,
    axis_name,
    metrics,
    mode,
    mpi_built,
    nccl_built,
    gloo_built,
    ccl_built,
    xla_built,
    tcp_built,
)
from .common.exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from .common.functions import (
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_object,
    allgather_object,
)
from .common.types import (
    Average,
    Sum,
    Adasum,
    Min,
    Max,
    Product,
    ReduceOp,
)
from .ops import (
    allreduce,
    allreduce_async,
    grouped_allreduce,
    allgather,
    allgather_async,
    broadcast,
    broadcast_async,
    alltoall,
    alltoall_async,
    reducescatter,
    join,
    barrier,
    poll,
    resolve_axis,
    synchronize,
)
from .common.goodput import step
from .ops.compression import Compression
from .ops.sync_batch_norm import SyncBatchNorm, sync_batch_stats
from .optim.distributed import (
    DistributedOptimizer,
    DistributedGradientTape,
    distributed_value_and_grad,
)
from .optim.zero import (
    ZeroState,
    zero_init,
)
from .optim.zero import state_specs as zero_state_specs
from .optim.zero import recut_state as zero_recut_state
from . import optim
from .parallel import mesh as mesh_utils
from .parallel.step import wrap_step

from . import elastic
from . import callbacks
from . import serving

__all__ = [k for k in dir() if not k.startswith("_")]
