"""Liveness plane: heartbeat failure detection with root-cause
attribution (docs/fault_tolerance.md "The liveness plane").

The fault-tolerance contract turns failures into HorovodInternalError —
but only when a data-plane I/O actually touches the dead peer. With the
default ``HOROVOD_TCP_TIMEOUT_SECONDS=0`` (unbounded) a *silently
wedged* rank — process alive, sockets open, kernel still ACKing, no
FIN ever — hangs the job forever. Production elastic systems bound this
with an always-on liveness layer (TorchElastic's agent heartbeats; the
φ-accrual failure detector of Hayashibara et al., SRDS 2004, is the
general shape — we use its degenerate fixed-threshold form, which is
what both TorchElastic and gloo's store timeouts implement in
practice).

Mechanics:

* every worker heartbeats the coordinator (and the coordinator acks
  every worker) on a ``HOROVOD_HEARTBEAT_INTERVAL_SECONDS`` cadence,
  over the existing mesh sockets with a dedicated frame tag
  (``HEALTH_CHANNEL``) — heartbeats are consumed by whichever thread
  happens to be reading a socket and are never awaited, so they cost
  nothing on the data path;
* ANY complete frame from a peer counts as liveness evidence (a rank
  mid-stream in a 100ms collective must not need a separate heartbeat
  to prove it is alive), and the monitor opportunistically drains
  sockets nobody is actively reading (the coordinator's sequential
  gather parks on one rank while the others' frames sit unread — they
  must not read as silence);
* a rank silent for more than ``HOROVOD_HEARTBEAT_MISS_LIMIT`` ×
  interval is **declared dead**: the verdict is latched as the peer's
  root cause on the transport (every later TransportError carries
  "rank 2 (host X) declared dead...", not "connection reset"), the
  socket is hard-closed so unbounded recvs parked on it unblock NOW,
  and the coordinator's next negotiation round broadcasts a tensor-less
  ERROR response naming the dead rank (the stall-abort path) so every
  survivor's pending handles fail with the same attributed reason;
* workers symmetrically declare the *coordinator* dead on missing acks
  and latch their engine's first-cause error directly;
* in elastic mode the coordinator also writes the verdict to the
  rendezvous KV (``health/verdict_e<epoch>``) so the driver evicts and
  blacklists the host that *failed*, not the one that reported.
"""
from __future__ import annotations

import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from . import tracing

logger = get_logger()

# Heartbeat frame payload:
#   <i rank> <B kind> <q sent_ns> <q echo_ns> <q echo_recv_ns> <hostname...>
# The three timestamps are the tracing plane's clock-offset estimator
# (tracing.estimate_offset): sent_ns is the sender's monotonic stamp,
# echo_ns is the receiver's own stamp the sender last saw, echo_recv_ns
# is the sender's local receipt time of it. Zero = no sample yet.
_BEAT = struct.Struct("<iBqqq")
KIND_BEAT = 0   # worker -> coordinator
KIND_ACK = 1    # coordinator -> worker

# KV scope the coordinator publishes verdicts under (consumed by
# runner/elastic/driver.py). The full key as the driver's put hook sees
# it is VERDICT_KEY_PREFIX + "<epoch>"; the value is encode_verdict().
VERDICT_SCOPE = "health"
VERDICT_KEY_PREFIX = VERDICT_SCOPE + "/verdict_e"


def encode_verdict(peer: int, host: str, reason: str) -> bytes:
    return f"{peer}|{host}|{reason}".encode()


def decode_verdict(value: bytes) -> Optional[Tuple[int, str, str]]:
    """(dead_rank, host, reason), or None for a malformed blob."""
    try:
        rank_s, host, reason = value.decode().split("|", 2)
        return int(rank_s), host, reason
    except (ValueError, UnicodeDecodeError):
        return None


def encode_beat(rank: int, kind: int, hostname: str, sent_ns: int = 0,
                echo_ns: int = 0, echo_recv_ns: int = 0) -> bytes:
    return _BEAT.pack(rank, kind, sent_ns, echo_ns, echo_recv_ns) \
        + hostname.encode("utf-8", "replace")


def decode_beat(payload: bytes) -> Tuple[int, int, str, int, int, int]:
    rank, kind, sent_ns, echo_ns, echo_recv_ns = _BEAT.unpack_from(payload, 0)
    return (rank, kind, payload[_BEAT.size:].decode("utf-8", "replace"),
            sent_ns, echo_ns, echo_recv_ns)


class FailureDetector:
    """Pure miss-limit math, separately testable: a peer whose last
    evidence of life is older than ``miss_limit × interval`` is dead.
    Declarations latch — a peer is declared at most once."""

    def __init__(self, peers, interval: float, miss_limit: int,
                 now: Optional[float] = None):
        self.interval = interval
        self.miss_limit = miss_limit
        self.window = interval * miss_limit
        now = time.monotonic() if now is None else now
        # Armed at construction: a peer that NEVER sends anything is
        # declared window seconds after the mesh came up, not never.
        self._last: Dict[int, float] = {p: now for p in peers}
        self._dead: Dict[int, float] = {}
        self._lock = threading.Lock()

    def note(self, peer: int, now: Optional[float] = None):
        """Evidence of life (heartbeat or any complete frame)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if peer in self._last and now > self._last[peer]:
                self._last[peer] = now

    def age(self, peer: int, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            return now - self._last.get(peer, now)

    def ages(self, now: Optional[float] = None) -> Dict[int, float]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return {p: now - t for p, t in self._last.items()}

    @property
    def dead(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._dead)

    def check(self, now: Optional[float] = None) -> List[Tuple[int, float]]:
        """Returns [(peer, silence_age)] NEWLY declared dead — each peer
        at most once, ever."""
        now = time.monotonic() if now is None else now
        newly: List[Tuple[int, float]] = []
        with self._lock:
            for peer, last in self._last.items():
                if peer in self._dead:
                    continue
                silence = now - last
                if silence > self.window:
                    self._dead[peer] = silence
                    newly.append((peer, silence))
        return newly


class HeartbeatMonitor:
    """One daemon thread per engine driving the liveness plane: send
    beats/acks, drain idle sockets, run the detector, act on verdicts.

    The coordinator (rank 0) watches every worker; workers watch the
    coordinator only — peer-to-peer wedges surface at the coordinator
    (the wedged rank stops gathering) and the verdict reaches everyone
    through the negotiation broadcast, so a full-mesh detector is not
    needed for bounded detection."""

    def __init__(self, backend, rank: int, size: int, interval: float,
                 miss_limit: int, engine=None, registry=None,
                 hostname: Optional[str] = None):
        from . import telemetry

        self.backend = backend
        self.rank = rank
        self.size = size
        self.interval = interval
        self.miss_limit = miss_limit
        self.engine = engine
        self.hostname = hostname if hostname is not None else env_cfg.get_str(
            env_cfg.HOSTNAME, "") or "?"
        self._watch = (list(range(1, size)) if rank == 0 else [0])
        self.detector = FailureDetector(self._watch, interval, miss_limit)
        self.peer_hosts: Dict[int, str] = {}
        self.verdicts: Dict[int, str] = {}
        # Clock-offset estimation for the tracing plane (docs/
        # tracing.md): each received beat/ack carries the sender's
        # stamp plus an echo of ours, one NTP-style sample per
        # exchange; the minimum-RTT sample bounds the alignment error
        # by rtt/2, so it wins. peer -> (peer_sent_ns, local_recv_ns)
        # feeds the echo of our next frame to that peer.
        self._last_remote: Dict[int, Tuple[int, int]] = {}
        self._offsets: Dict[int, Tuple[int, int]] = {}  # peer -> (off, rtt)
        self._first_declared: Optional[float] = None
        self._escalated = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        registry = registry if registry is not None \
            else telemetry.default_registry()
        self._m_sent = registry.counter(
            "horovod_heartbeats_sent_total",
            "Liveness beats/acks written to peer sockets")
        self._m_recv = registry.counter(
            "horovod_heartbeats_received_total",
            "Liveness beats/acks consumed from peer sockets")
        self._m_dead = registry.counter(
            "horovod_ranks_declared_dead_total",
            "Ranks this process declared dead by heartbeat silence")
        self._gauges = {}
        for peer in self._watch:
            g = registry.gauge(
                "horovod_heartbeat_age_seconds",
                "Seconds since the last evidence of life from a peer",
                labels={"peer": str(peer)})
            fn = lambda p=peer: self.detector.age(p)  # noqa: E731
            g.set_function(fn)
            self._gauges[peer] = (g, fn)
        backend.set_health_callback(self._on_health_frame)

    # ------------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="hvd-health", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        # Pass our own callback: a replacement engine's monitor may
        # already have taken these gauges over (telemetry ownership
        # contract), and a late stop() must not freeze ITS ages.
        for g, fn in self._gauges.values():
            g.clear_function(fn)

    # ------------------------------------------------------------------
    def status(self) -> dict:
        """Live liveness view for /status (docs/metrics.md)."""
        ages = self.detector.ages()
        return {
            "enabled": True,
            "role": "coordinator" if self.rank == 0 else "worker",
            "interval_seconds": self.interval,
            "miss_limit": self.miss_limit,
            "peers": {
                str(p): {
                    "age_seconds": round(ages.get(p, -1.0), 3),
                    "host": self.peer_hosts.get(p, ""),
                    "clock_offset_ns": self._offsets.get(p, (None, 0))[0],
                }
                for p in self._watch
            },
            "dead": dict(self.verdicts),
        }

    # ------------------------------------------------------------------
    def _on_health_frame(self, peer: int, payload: bytes):
        """Runs on WHATEVER thread read the frame (demux reader, idle
        drain) — keep it to dict stores."""
        try:
            rank, kind, host, sent_ns, echo_ns, echo_recv_ns = \
                decode_beat(payload)
        except (struct.error, UnicodeDecodeError):  # pragma: no cover
            return
        self._m_recv.inc()
        if host:
            self.peer_hosts[peer] = host
        now_ns = clock.mono_ns()
        if sent_ns:
            self._last_remote[peer] = (sent_ns, now_ns)
            if echo_ns:
                off, rtt = tracing.estimate_offset(
                    sent_ns, echo_ns, echo_recv_ns, now_ns)
                cur = self._offsets.get(peer)
                if cur is None or rtt <= cur[1]:
                    self._offsets[peer] = (off, rtt)
        self.detector.note(peer)

    def clock_offsets(self) -> Dict[int, int]:
        """Best (minimum-RTT) peer-clock offsets in ns: peer clock
        minus this process's clock. The merged-trace renderer subtracts
        them to put every rank's spans on one timebase."""
        return {p: o for p, (o, _rtt) in self._offsets.items()}

    def _loop(self):
        from . import fault_injection

        inj = fault_injection.get_injector()
        while not self._stop.wait(self.interval):
            if inj.active and inj.wedged:
                # A wedged process's monitor is as frozen as the rest
                # of it: stop beating, stop detecting, park.
                fault_injection.FaultInjector._park_forever()
            try:
                self._tick()
            except Exception:  # pragma: no cover - defensive
                logger.exception("heartbeat tick failed")

    def _tick(self):
        kind = KIND_ACK if self.rank == 0 else KIND_BEAT
        # Beats/acks go out BEFORE any drain can stall (send_async only
        # enqueues): one peer wedged mid-frame must not starve the acks
        # every other peer's detector depends on. Per-peer payloads:
        # each carries the echo of THAT peer's last stamp for the
        # clock-offset estimator.
        for peer in self._watch:
            echo_ns, echo_recv_ns = self._last_remote.get(peer, (0, 0))
            payload = encode_beat(self.rank, kind, self.hostname,
                                  sent_ns=clock.mono_ns(),
                                  echo_ns=echo_ns,
                                  echo_recv_ns=echo_recv_ns)
            try:
                self.backend.send_async(
                    peer, payload, channel=_health_channel())
                self._m_sent.inc()
            except Exception:
                # Severed/dead peer: the detector owns the verdict.
                pass
        for peer in self._watch:
            # Fold transport-level receive activity into the detector
            # BEFORE draining, so a frame that an active reader consumed
            # since the last tick counts.
            act = self.backend.peer_activity(peer)
            if act is not None:
                self.detector.note(peer, act)
            try:
                # The drain never blocks (it only reads bytes already
                # in the kernel buffer, stashing a mid-arrival frame for
                # the next pass), so this single thread keeps the beat
                # cadence for every watched peer.
                self.backend.try_drain_idle(peer)
            except Exception:  # pragma: no cover - defensive
                logger.exception("idle drain for peer %d failed", peer)
            act = self.backend.peer_activity(peer)
            if act is not None:
                self.detector.note(peer, act)
        for peer, silence in self.detector.check():
            self._declare_dead(peer, silence)
        self._maybe_escalate()

    @property
    def window(self) -> float:
        return self.detector.window

    # ------------------------------------------------------------------
    def _declare_dead(self, peer: int, silence: float):
        host = self.peer_hosts.get(peer, "")
        who = f"rank {peer} (host {host})" if host else f"rank {peer}"
        if self.rank != 0 and peer == 0:
            who = f"coordinator {who}"
        reason = (
            f"{who} declared dead by rank {self.rank}: no heartbeat or "
            f"traffic for {silence:.1f}s (> HOROVOD_HEARTBEAT_MISS_LIMIT="
            f"{self.miss_limit} x HOROVOD_HEARTBEAT_INTERVAL_SECONDS="
            f"{self.interval:g})"
        )
        # If the fleet has a drain in flight the silence is probably the
        # PLAN (a preempted peer checkpointing, then exiting) — say so,
        # so operators and the badput attribution don't read an
        # announced preemption as a mystery failure.
        try:
            from . import drain as drain_mod

            if drain_mod.fleet_draining():
                reason += " [peer was draining: announced preemption]"
        except Exception:  # pragma: no cover - attribution only
            pass
        logger.error("liveness: %s", reason)
        self._m_dead.inc()
        from . import events as events_mod

        events_mod.emit(events_mod.HEALTH_VERDICT,
                        severity=events_mod.ERROR, rank=self.rank,
                        peer=peer, host=host,
                        silence_s=round(silence, 1))
        self.verdicts[peer] = reason
        if self._first_declared is None:
            self._first_declared = time.monotonic()
        # 1. Latch the verdict as the peer's root cause and hard-close
        #    the socket: every I/O parked on it unblocks with the
        #    attributed TransportError, bounded regardless of
        #    HOROVOD_TCP_TIMEOUT_SECONDS.
        self.backend.declare_dead(peer, reason)
        if self.rank == 0:
            # 2. Coordinator: the controller's next negotiation round
            #    hits the severed peer, catches the attributed error,
            #    and broadcasts the tensor-less ERROR verdict to the
            #    survivors (engine/controller.py) — the monitor itself
            #    must NOT kill the engine yet or the broadcast never
            #    happens. It also publishes the verdict to the
            #    rendezvous KV for the elastic driver's eviction fast
            #    path.
            self._publish_verdict(peer, host, reason)
        else:
            # Workers have nobody to tell: fail the engine directly so
            # a loop parked outside a control recv (backpressure wait,
            # fence drain) still dies within the window.
            self._latch_engine(reason, peer)

    def _maybe_escalate(self):
        """Coordinator backstop: if the engine has not died within one
        extra interval of the first declaration (e.g. the background
        loop is parked in a fence drain and never reaches the
        negotiation round that would broadcast the verdict), latch the
        first verdict directly — survivors then learn through the
        FIN/ack-loss cascade instead of the clean broadcast, but
        detection stays bounded."""
        if (self.rank != 0 or self._escalated or not self.verdicts
                or self._first_declared is None or self.engine is None):
            return
        if self.engine._fatal_error is not None:
            self._escalated = True
            return
        if time.monotonic() - self._first_declared > 2 * self.interval:
            self._escalated = True
            reason = next(iter(self.verdicts.values()))
            self._latch_engine(reason, next(iter(self.verdicts)))

    def _latch_engine(self, reason: str, peer: int):
        if self.engine is None:
            return
        from .exceptions import TransportError

        self.engine._latch_fatal(TransportError(
            reason, peer=peer, reporter=self.rank, root_cause=reason))

    def _publish_verdict(self, peer: int, host: str, reason: str):
        try:
            from ..backend import elastic_env

            rdv = elastic_env._rendezvous()
            if rdv is None:
                return
            epoch = elastic_env._current_epoch()
            key = f"verdict_e{epoch if epoch is not None else 0}"
            rdv.put(VERDICT_SCOPE, key, encode_verdict(peer, host, reason))
        except Exception:  # best-effort: the broadcast is the main path
            logger.warning("could not publish liveness verdict to the "
                           "rendezvous KV", exc_info=True)


def _health_channel() -> int:
    from ..backend.base import HEALTH_CHANNEL

    return HEALTH_CHANNEL


def maybe_start_monitor(engine) -> Optional[HeartbeatMonitor]:
    """Engine hook: start the liveness plane when enabled and the
    backend supports it (the TCP mesh; local/threaded backends have no
    sockets to watch)."""
    backend = engine.backend
    if (not env_cfg.heartbeat_enabled() or engine.size <= 1
            or not hasattr(backend, "set_health_callback")):
        return None
    interval = env_cfg.heartbeat_interval_seconds()
    miss = env_cfg.heartbeat_miss_limit()
    mon = HeartbeatMonitor(
        backend, engine.rank, engine.size, interval=interval,
        miss_limit=miss, engine=engine, registry=engine.registry,
    )
    mon.start()
    logger.debug(
        "liveness plane armed: interval=%.3gs miss_limit=%d (window %.3gs)",
        interval, miss, interval * miss)
    return mon
