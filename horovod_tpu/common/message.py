"""Control-plane wire messages: Request / Response and their lists.

Re-design of the reference's FlatBuffers-based protocol
(ref: horovod/common/message.h:50-149, horovod/common/wire/message.fbs:18-40).
We use a compact length-prefixed binary codec (struct-packed) instead of
FlatBuffers: messages are tiny (names + shapes), the codec has zero
dependencies, and the identical layout is implemented by the C++ engine
(horovod_tpu/cc) so both engines speak the same wire format.
"""
from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .types import DataType


class RequestType(enum.IntEnum):
    """(ref: horovod/common/message.h:50-52)"""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7


class ResponseType(enum.IntEnum):
    """(ref: horovod/common/message.h:147-149)"""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7
    ERROR = 8


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack("<I", len(b)) + b


def _unpack_str(buf: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return buf[off : off + n].decode("utf-8"), off + n


def _pack_i64list(xs) -> bytes:
    return struct.pack("<I", len(xs)) + struct.pack(f"<{len(xs)}q", *xs)


def _unpack_i64list(buf: bytes, off: int):
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    xs = list(struct.unpack_from(f"<{n}q", buf, off))
    return xs, off + 8 * n


@dataclass
class Request:
    """A worker's announcement that one tensor is ready for a collective
    (ref: message.h Request; fields mirror wire/message.fbs:18-29)."""

    request_rank: int = 0
    request_type: RequestType = RequestType.ALLREDUCE
    tensor_type: DataType = DataType.FLOAT32
    tensor_name: str = ""
    root_rank: int = 0
    device: int = 0
    tensor_shape: Tuple[int, ...] = ()
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # ReduceOp for ALLREDUCE (SUM/MIN/MAX/PRODUCT; AVERAGE lowers to
    # SUM+postscale before enqueue). The reference encodes this in the
    # op layer; here it rides the wire so the coordinator can validate
    # cross-rank agreement (ref: message.h Request op semantics).
    reduce_op: int = 0

    def serialize(self) -> bytes:
        head = struct.pack(
            "<iiiiiddi",
            self.request_rank,
            int(self.request_type),
            int(self.tensor_type),
            self.root_rank,
            self.device,
            self.prescale_factor,
            self.postscale_factor,
            self.reduce_op,
        )
        return head + _pack_str(self.tensor_name) + _pack_i64list(self.tensor_shape)

    @staticmethod
    def deserialize(buf: bytes, off: int = 0) -> Tuple["Request", int]:
        rr, rt, tt, root, dev, pre, post, rop = struct.unpack_from(
            "<iiiiiddi", buf, off)
        off += struct.calcsize("<iiiiiddi")
        name, off = _unpack_str(buf, off)
        shape, off = _unpack_i64list(buf, off)
        return (
            Request(rr, RequestType(rt), DataType(tt), name, root, dev,
                    tuple(shape), pre, post, rop),
            off,
        )


@dataclass
class RequestList:
    """(ref: message.h RequestList; shutdown flag at message.h:120-135)

    `telemetry` is an optional opaque blob a rank piggybacks on its
    per-cycle gather so rank 0 can hold a fleet metrics view
    (common/telemetry.py FleetView) without a second collective. It is a
    TRAILING optional field: decoders that stop after `requests` (the
    C++ engine's codec) stay wire-compatible, and this decoder treats a
    missing tail as None.
    """

    requests: List[Request] = field(default_factory=list)
    shutdown: bool = False
    telemetry: Optional[bytes] = None

    def serialize(self) -> bytes:
        out = struct.pack("<?I", self.shutdown, len(self.requests))
        for r in self.requests:
            out += r.serialize()
        if self.telemetry is not None:
            out += struct.pack("<I", len(self.telemetry)) + self.telemetry
        return out

    @staticmethod
    def deserialize(buf: bytes) -> "RequestList":
        shutdown, n = struct.unpack_from("<?I", buf, 0)
        off = struct.calcsize("<?I")
        reqs = []
        for _ in range(n):
            r, off = Request.deserialize(buf, off)
            reqs.append(r)
        telemetry = None
        if off + 4 <= len(buf):
            (tn,) = struct.unpack_from("<I", buf, off)
            off += 4
            telemetry = buf[off : off + tn]
        return RequestList(reqs, shutdown, telemetry)


@dataclass
class Response:
    """Coordinator's instruction to execute a (possibly fused) collective
    (ref: message.h Response; wire/message.fbs:31-40)."""

    response_type: ResponseType = ResponseType.ALLREDUCE
    tensor_names: List[str] = field(default_factory=list)
    error_message: str = ""
    devices: List[int] = field(default_factory=list)
    # Allgather: aggregated first-dim sizes per rank; Alltoall: recv splits.
    tensor_sizes: List[int] = field(default_factory=list)
    tensor_type: DataType = DataType.FLOAT32
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    last_joined_rank: int = -1
    # Per-tensor shapes (parallel to tensor_names). Lets every rank —
    # including joined ranks that never issued the request — populate the
    # response cache with an identical key, keeping cache-bit assignment
    # rank-consistent (ref: response_cache.cc put-from-response).
    tensor_shapes: List[Tuple[int, ...]] = field(default_factory=list)
    reduce_op: int = 0
    # Executor channel the coordinator assigned (round-robin over
    # HOROVOD_NUM_CHANNELS for non-fence responses; fences stay 0).
    # Wire-carried so every rank — workers and joined ranks replaying
    # cached responses alike — executes the same response on the same
    # channel in the same per-channel FIFO order, the ordering invariant
    # that keeps concurrent collectives from deadlocking.
    channel: int = 0
    # Tracing-plane correlation id the coordinator assigned
    # (common/tracing.py). Wire-carried like the channel id so every
    # rank's spans for this collective — negotiation, queue dwell,
    # executor run, backend phases — share one id in the merged trace.
    # Cache-replayed responses use a deterministic per-rank replay
    # sequence instead (odd id space; the cache fast path exchanges no
    # per-response bytes).
    trace_id: int = 0
    # Wire codec id (common/compression.py CODEC_*) the coordinator
    # assigned for this response's data-plane frames — 0 = full-width.
    # Wire-carried next to the channel id for the same reason: codec
    # choice MUST be collectively agreed (a half-width frame meeting a
    # full-width reader is a desync) and cache-replay-stable (the
    # cached Response carries it, so every replay re-applies the codec
    # it was negotiated with, on every rank, joined ranks included).
    codec: int = 0

    def serialize(self) -> bytes:
        out = struct.pack(
            "<iiddiiiqi",
            int(self.response_type),
            int(self.tensor_type),
            self.prescale_factor,
            self.postscale_factor,
            self.last_joined_rank,
            self.reduce_op,
            self.channel,
            self.trace_id,
            self.codec,
        )
        out += struct.pack("<I", len(self.tensor_names))
        for n in self.tensor_names:
            out += _pack_str(n)
        out += _pack_str(self.error_message)
        out += _pack_i64list(self.devices)
        out += _pack_i64list(self.tensor_sizes)
        out += struct.pack("<I", len(self.tensor_shapes))
        for shp in self.tensor_shapes:
            out += _pack_i64list(shp)
        return out

    @staticmethod
    def deserialize(buf: bytes, off: int = 0) -> Tuple["Response", int]:
        rt, tt, pre, post, ljr, rop, chan, trace_id, codec = \
            struct.unpack_from("<iiddiiiqi", buf, off)
        off += struct.calcsize("<iiddiiiqi")
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        names = []
        for _ in range(n):
            s, off = _unpack_str(buf, off)
            names.append(s)
        err, off = _unpack_str(buf, off)
        devices, off = _unpack_i64list(buf, off)
        sizes, off = _unpack_i64list(buf, off)
        (nshapes,) = struct.unpack_from("<I", buf, off)
        off += 4
        shapes = []
        for _ in range(nshapes):
            shp, off = _unpack_i64list(buf, off)
            shapes.append(tuple(int(d) for d in shp))
        return (
            Response(ResponseType(rt), names, err, [int(d) for d in devices],
                     sizes, DataType(tt), pre, post, ljr, shapes, rop, chan,
                     trace_id, codec),
            off,
        )


@dataclass
class ResponseList:
    """(ref: message.h ResponseList)"""

    responses: List[Response] = field(default_factory=list)
    shutdown: bool = False

    def serialize(self) -> bytes:
        out = struct.pack("<?I", self.shutdown, len(self.responses))
        for r in self.responses:
            out += r.serialize()
        return out

    @staticmethod
    def deserialize(buf: bytes) -> "ResponseList":
        shutdown, n = struct.unpack_from("<?I", buf, 0)
        off = struct.calcsize("<?I")
        resps = []
        for _ in range(n):
            r, off = Response.deserialize(buf, off)
            resps.append(r)
        return ResponseList(resps, shutdown)
