"""Distributed tracing plane: cross-rank collective spans + an
always-on flight recorder (docs/tracing.md).

The reference ships a per-rank Chrome-trace timeline
(ref: horovod/common/timeline.{h,cc}) and PR 2's telemetry answers
"how much / how often" — neither answers "where did this step's 40 ms
go, and which rank made everyone wait". This module is the missing
layer, three pieces:

* **Span API + flight recorder** — `Tracer.span()` records
  (trace_id, name, category, t0, duration, thread) tuples into a
  fixed-size in-memory ring (`SpanRecorder`): append-only,
  monotonic-ns stamps from the shared `utils.clock` anchor, always on,
  never any I/O on the hot path. The ring overwrites its oldest events
  (counted in ``horovod_trace_events_dropped_total{source="recorder"}``)
  so the last ``HOROVOD_TRACE_BUFFER_EVENTS`` events are always
  available — a black-box flight recorder, dumped on failure.

* **Cross-rank correlation** — the coordinator assigns a trace id per
  `Response`, carried on the wire (common/message.py, the same
  trailing-field pattern as the executor channel id), so every rank's
  spans for one collective share an id. Cache-replayed responses get
  ids from a deterministic per-rank replay sequence (odd id space —
  the fast path exchanges no per-response bytes, but every rank emits
  the same cached responses in the same order, so local counters
  agree). The active id is a thread-local scope (`trace_scope`) the
  engine sets around each response; backend spans inherit it
  implicitly, including across the hop onto a persistent TCP sender
  thread (captured at enqueue).

* **Collection + rendering** — each rank piggybacks new-event batches
  on the telemetry push it already gathers to rank 0
  (engine/controller.py); rank 0's `TraceCollector` accumulates them
  (dedup by per-rank sequence number), aligns clocks with per-peer
  offsets estimated from heartbeat send/ack RTTs (`estimate_offset`,
  fed by common/health.py; wall-clock anchors as the fallback), and
  `render_chrome` merges everything into one Perfetto/Chrome document
  with one process lane per rank — served at `/trace`, dumped to
  ``HOROVOD_TRACE_FILE``, and stitched into failure post-mortems
  under ``HOROVOD_TRACE_DIR``.

Event tuple layout (also the wire/JSON batch format):

    (seq, trace_id, name, cat, t0_ns, dur_ns, thread, args|None)

``seq`` is a per-rank monotonically increasing index (the dedup key);
``t0_ns`` is a raw ``monotonic_ns`` stamp — rendering subtracts the
per-rank clock offset and the coordinator's anchor.
"""
from __future__ import annotations

import contextlib
import glob
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..utils import atomic_file, clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

# Span categories (the critical-path analyzer attributes time by these).
CAT_NEGOTIATE = "negotiate"
CAT_QUEUE = "queue"
CAT_EXEC = "exec"
CAT_XFER = "xfer"
CAT_COMPUTE = "compute"
# Step-demarcation spans from the goodput ledger (docs/goodput.md):
# one X event per training step, args carry the step number and its
# exposed-comm share — what critical_path.py groups collectives under.
CAT_STEP = "step"


# ---------------------------------------------------------------------------
# Thread-local trace-id scope (the engine sets it around each response;
# same shape as backend/base.py's channel scope).

_trace_ctx = threading.local()


def current_trace() -> int:
    """Trace id spans on the calling thread inherit; 0 outside any
    scope (control plane, heartbeats, direct backend use)."""
    return getattr(_trace_ctx, "trace_id", 0)


@contextlib.contextmanager
def trace_scope(trace_id: int):
    prev = getattr(_trace_ctx, "trace_id", None)
    _trace_ctx.trace_id = trace_id
    try:
        yield
    finally:
        if prev is None:
            del _trace_ctx.trace_id
        else:
            _trace_ctx.trace_id = prev


# ---------------------------------------------------------------------------
# Flight recorder

# Per-thread cached thread name: threading.current_thread().name costs
# ~550ns; the thread-local getattr ~100ns. Names never change for the
# engine's long-lived workers.
_tname_cache = threading.local()


def _thread_name() -> str:
    n = getattr(_tname_cache, "v", None)
    if n is None:
        n = _tname_cache.v = threading.current_thread().name
    return n


class SpanRecorder:
    """Fixed-size ring of trace events: append-only, no I/O, overwrite
    on wrap. The per-rank sequence number never resets, so consumers
    read incrementally with `batch_since` and overwrites are exactly
    `total - retained` (the drop accounting).

    Hot-path design: `append` is a plain `list.append` (GIL-atomic, no
    lock) with the seq drawn from an `itertools.count` (also atomic);
    the ring is enforced by an amortized trim once the list doubles
    past capacity — ~1 lock acquisition per `capacity` appends instead
    of one per event, which is what keeps the always-on recorder under
    the <2% overhead budget on a saturated box. Between trims the
    recorder briefly retains MORE than `capacity` events (never
    fewer); `snapshot` presents exactly the last `capacity`."""

    __slots__ = ("capacity", "_buf", "_seq", "_trim_at", "_lock",
                 "_m_dropped")

    def __init__(self, capacity: int, registry=None):
        self.capacity = max(int(capacity), 0)
        self._buf: List[tuple] = []
        self._seq = itertools.count()
        self._trim_at = 2 * self.capacity
        self._lock = threading.Lock()
        self._m_dropped = None
        if self.capacity and registry is not None:
            self._m_dropped = registry.counter(
                "horovod_trace_events_dropped_total",
                "Trace events lost before reaching an output (flight-"
                "recorder ring overwrites, timeline writer-queue drops)",
                labels={"source": "recorder"})

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def append(self, trace_id: int, name: str, cat: str, t0_ns: int,
               dur_ns: int, thread: str, args: Optional[dict] = None):
        if not self.capacity:
            return
        buf = self._buf
        buf.append((next(self._seq), trace_id, name, cat, t0_ns, dur_ns,
                    thread, args))
        if len(buf) >= self._trim_at:
            self._trim()

    def _trim(self):
        # Amortized ring enforcement. Overwriting events that were
        # never dumped IS a drop: without the counter a truncated
        # post-mortem would read as the whole story. (The counter
        # advances at trim time; the `dropped` property is exact.)
        with self._lock:
            excess = len(self._buf) - self.capacity
            if excess > 0:
                del self._buf[:excess]
                if self._m_dropped is not None:
                    self._m_dropped.inc(excess)

    def _total(self) -> int:
        buf = self._buf
        return buf[-1][0] + 1 if buf else 0

    def depth(self) -> int:
        return min(len(self._buf), self.capacity)

    @property
    def dropped(self) -> int:
        """Events no longer retained by the ring (exact)."""
        return max(self._total() - self.depth(), 0)

    def snapshot(self) -> List[tuple]:
        """The last `capacity` retained events, oldest first."""
        with self._lock:
            evs = list(self._buf)
        # Concurrent appenders may interleave adjacent seqs; order by
        # seq so consumers (and the ring cut) see the true sequence.
        evs.sort(key=lambda e: e[0])
        return evs[-self.capacity:]

    def batch_since(self, cursor: int, limit: int = 4096
                    ) -> Tuple[List[tuple], int]:
        """Events with seq >= cursor (the OLDEST `limit` of them) and
        the next cursor. Oldest-first with the cursor advancing only
        past what was returned, so a backlog bigger than one batch
        drains across successive pushes instead of being silently
        skipped; events the ring overwrote before collection show as a
        cursor gap and are already counted by the trim drop counter.
        Non-destructive: the ring keeps its last-N for post-mortems
        regardless of collection."""
        evs = [e for e in self.snapshot() if e[0] >= cursor]
        if len(evs) > limit:
            evs = evs[:limit]
        nxt = evs[-1][0] + 1 if evs else self._total()
        return evs, nxt


# ---------------------------------------------------------------------------
# Span API

class _Span:
    """Context manager recording one complete event on exit (the E side
    fires even when the body raises, so a failed op still leaves its
    span in the flight recorder — that IS the post-mortem story).

    The exit path is deliberately inlined — no helper calls — because
    span cost on the data-plane hot loops is dominated by Python call
    overhead, and the always-on recorder carries a <2% overhead budget
    (docs/tracing.md)."""

    __slots__ = ("_rec", "_name", "_cat", "_trace", "_args", "_t0")

    def __init__(self, rec: SpanRecorder, name: str, cat: str,
                 trace_id: Optional[int], args: Optional[dict]):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._trace = trace_id
        self._args = args

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic_ns()
        tid = self._trace
        if tid is None:
            tid = getattr(_trace_ctx, "trace_id", 0)
        tn = getattr(_tname_cache, "v", None)
        if tn is None:
            tn = _tname_cache.v = threading.current_thread().name
        rec = self._rec
        buf = rec._buf
        buf.append((next(rec._seq), tid, self._name, self._cat, self._t0,
                    t1 - self._t0, tn, self._args))
        if len(buf) >= rec._trim_at:
            rec._trim()
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Span recorder + dump helpers for one engine (injectable per
    engine like the telemetry registries; real one-process-per-rank
    jobs construct it on the process default registry)."""

    def __init__(self, registry=None, capacity: Optional[int] = None):
        if capacity is None:
            capacity = env_cfg.trace_buffer_events()
        if capacity > 0 and registry is None:
            from . import telemetry

            registry = telemetry.default_registry()
        self.recorder = SpanRecorder(capacity, registry)
        self.enabled = capacity > 0
        self.last_dump: Optional[str] = None

    def span(self, name: str, cat: str = CAT_EXEC,
             trace_id: Optional[int] = None, args: Optional[dict] = None):
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self.recorder, name, cat, trace_id, args)

    def emit(self, name: str, cat: str, t0_ns: int, dur_ns: int,
             trace_id: Optional[int] = None, args: Optional[dict] = None):
        """Record a span with explicit timestamps (queue dwell, sender
        dwell — measured across threads, not with a context manager).
        Inlined like _Span.__exit__ — same hot-path budget."""
        if not self.enabled:
            return
        if trace_id is None:
            trace_id = getattr(_trace_ctx, "trace_id", 0)
        tn = getattr(_tname_cache, "v", None)
        if tn is None:
            tn = _tname_cache.v = threading.current_thread().name
        rec = self.recorder
        buf = rec._buf
        buf.append((next(rec._seq), trace_id, name, cat, t0_ns,
                    max(dur_ns, 0), tn, args))
        if len(buf) >= rec._trim_at:
            rec._trim()

    def instant(self, name: str, cat: str = "mark",
                trace_id: Optional[int] = None,
                args: Optional[dict] = None):
        self.emit(name, cat, clock.mono_ns(), 0, trace_id, args)

    def status(self) -> dict:
        """Recorder state for the /status `trace` view."""
        return {
            "enabled": self.enabled,
            "buffer_events": self.recorder.capacity,
            "depth": self.recorder.depth(),
            "dropped": self.recorder.dropped,
            "last_dump": self.last_dump,
        }

    # -- failure post-mortems ------------------------------------------
    def dump_flight(self, path: str, rank: int,
                    extra: Optional[dict] = None) -> str:
        """Write this rank's full flight-recorder contents (plus the
        process clock anchor, so offline stitching can align it) as one
        JSON document. The black-box dump on engine death."""
        doc = {
            "rank": rank,
            "anchor": clock.anchor_meta(),
            "dropped": self.recorder.dropped,
            "events": self.recorder.snapshot(),
        }
        if extra:
            doc.update(extra)
        atomic_file.atomic_write(path, lambda f: json.dump(doc, f),
                                 mode="w")
        self.last_dump = path
        return path


# Shared inert tracer: the default for backends constructed outside an
# engine (tests, direct use). Never touches a registry.
NULL_TRACER = Tracer(capacity=0)


# ---------------------------------------------------------------------------
# Clock alignment

def estimate_offset(remote_sent_ns: int, echo_ns: int, echo_recv_ns: int,
                    local_recv_ns: int) -> Tuple[int, int]:
    """NTP-style offset estimate from one heartbeat exchange. The
    incoming frame carries the peer's send stamp (`remote_sent_ns`, its
    clock), an echo of OUR last stamp it saw (`echo_ns`, our clock) and
    its local receipt time of that stamp (`echo_recv_ns`, its clock);
    we observe arrival at `local_recv_ns` (our clock).

        rtt    = (local_recv - echo) - (remote_sent - echo_recv)
        offset = remote_sent - (local_recv - rtt/2)

    Returns (offset_ns, rtt_ns): offset is the peer clock MINUS ours —
    subtract it from a peer timestamp to land on our timebase. Estimates
    from low-RTT exchanges bound the error by rtt/2 (the classic NTP
    argument), which is why the health monitor keeps the minimum-RTT
    sample."""
    rtt = (local_recv_ns - echo_ns) - (remote_sent_ns - echo_recv_ns)
    if rtt < 0:
        rtt = 0
    offset = remote_sent_ns - (local_recv_ns - rtt // 2)
    return offset, rtt


def wall_anchor_offset(remote_anchor: Optional[dict],
                       local_anchor: Optional[dict]) -> int:
    """Fallback peer-clock offset from the wall-clock identity each
    process stamps into its trace blobs (utils/clock.anchor_meta):
    assume the wall clocks agree (same box, or NTP-disciplined hosts)
    and solve for the monotonic-clock offset. Exact for in-process
    multi-rank tests (same anchors → 0)."""
    try:
        return int(
            (remote_anchor["mono_anchor_ns"] - remote_anchor["wall_anchor_ns"])
            - (local_anchor["mono_anchor_ns"] - local_anchor["wall_anchor_ns"])
        )
    except (KeyError, TypeError):
        return 0


# ---------------------------------------------------------------------------
# Rank-0 collection

class TraceCollector:
    """Per-rank event batches accumulated on the coordinator (bounded
    to the flight-recorder capacity per rank), deduplicated by the
    per-rank sequence number so overlapping batches are harmless."""

    def __init__(self, size: int, capacity: Optional[int] = None):
        if capacity is None:
            capacity = env_cfg.trace_buffer_events()
        self.size = size
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._events: Dict[int, deque] = {}
        self._anchors: Dict[int, dict] = {}
        self._last_seq: Dict[int, int] = {}

    def ingest(self, rank: int, events: List, anchor: Optional[dict] = None):
        with self._lock:
            dq = self._events.get(rank)
            if dq is None:
                dq = self._events[rank] = deque(maxlen=self.capacity)
            last = self._last_seq.get(rank, -1)
            for e in events:
                try:
                    seq = int(e[0])
                except (TypeError, ValueError, IndexError):
                    continue
                if seq <= last:
                    continue
                dq.append(tuple(e))
                last = seq
            self._last_seq[rank] = last
            if anchor:
                self._anchors[rank] = dict(anchor)

    def ingest_blob(self, rank: int, blob: bytes):
        """Extract the span batch a rank piggybacked on its telemetry
        push; tolerant of blobs without one (older ranks, tracing
        off)."""
        try:
            d = json.loads(blob.decode("utf-8"))
            spans = d.get("spans")
            anchor = d.get("anchor")
        except Exception:
            return  # a malformed blob must never take down the cycle loop
        if spans:
            self.ingest(rank, spans, anchor)

    def segments(self, offsets: Optional[Dict[int, int]] = None,
                 local_anchor: Optional[dict] = None) -> List[dict]:
        """Per-rank segments for `render_chrome`. Offsets: the health
        plane's RTT-estimated peer offsets when available, wall-anchor
        alignment otherwise."""
        offsets = offsets or {}
        out = []
        with self._lock:
            ranks = sorted(self._events)
            for r in ranks:
                off = offsets.get(r)
                anchor = self._anchors.get(r)
                if off is None:
                    off = wall_anchor_offset(anchor, local_anchor) \
                        if anchor and local_anchor else 0
                out.append({
                    "rank": r,
                    "events": list(self._events[r]),
                    "anchor": anchor,
                    "offset_ns": int(off),
                })
        return out

    def status(self) -> Dict[str, int]:
        with self._lock:
            return {str(r): len(dq) for r, dq in sorted(self._events.items())}


# ---------------------------------------------------------------------------
# Chrome/Perfetto rendering

def chrome_events(segments: List[dict], base_ns: int) -> List[dict]:
    """Merge per-rank event segments into one Chrome-trace event list:
    pid = rank (one process lane per rank), tid = thread within the
    rank, ts = microseconds on the coordinator's timebase (each event's
    raw monotonic stamp minus the segment's peer-clock offset minus
    `base_ns`). Every X event carries its trace id in args, which is
    what the Perfetto query (and scripts/critical_path.py) correlates
    across lanes."""
    out: List[dict] = []
    for seg in segments:
        pid = int(seg["rank"])
        host = (seg.get("anchor") or {}).get("host") or seg.get("host")
        pname = f"rank {pid}" + (f" ({host})" if host else "")
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "args": {"name": pname}})
        out.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                    "args": {"sort_index": pid}})
        offset = int(seg.get("offset_ns", 0))
        tids: Dict[str, int] = {}
        for ev in seg["events"]:
            try:
                _, trace_id, name, cat, t0, dur, thread, args = ev
            except (TypeError, ValueError):
                continue
            tid = tids.get(thread)
            if tid is None:
                tid = tids[thread] = len(tids) + 1
                out.append({"ph": "M", "name": "thread_name", "pid": pid,
                            "tid": tid, "args": {"name": str(thread)}})
            ev_args = {"trace_id": trace_id}
            if args:
                ev_args.update(args)
            out.append({
                "ph": "X",
                "name": str(name),
                "cat": str(cat),
                "pid": pid,
                "tid": tid,
                "ts": (int(t0) - offset - base_ns) / 1e3,
                "dur": int(dur) / 1e3,
                "args": ev_args,
            })
    return out


def render_chrome(segments: List[dict], base_ns: Optional[int] = None,
                  metadata: Optional[dict] = None) -> dict:
    """Full Chrome-trace document (object form: Perfetto ignores extra
    top-level keys, so the clock anchor and any post-mortem verdict
    ride along)."""
    if base_ns is None:
        base_ns = clock.MONO_ANCHOR_NS
    doc = {
        "traceEvents": chrome_events(segments, base_ns),
        "displayTimeUnit": "ms",
        "horovod_clock": clock.anchor_meta(),
    }
    if metadata:
        doc.update(metadata)
    return doc


# ---------------------------------------------------------------------------
# Failure post-mortems

FLIGHT_PREFIX = "flight_rank"
POSTMORTEM_NAME = "postmortem.json"


def flight_path(trace_dir: str, rank: int) -> str:
    return os.path.join(trace_dir, f"{FLIGHT_PREFIX}{rank}.json")


def stitch_post_mortem(trace_dir: str, verdict: str = "",
                       health: Optional[dict] = None,
                       expect_ranks: Optional[int] = None,
                       grace_s: float = 5.0,
                       out_name: str = POSTMORTEM_NAME,
                       offsets: Optional[Dict[int, int]] = None
                       ) -> Optional[str]:
    """Coordinator-side black box: read every rank's flight dump under
    `trace_dir` (polling up to `grace_s` for stragglers still writing —
    the dumps race the stitch on an engine death), align clocks via
    the health plane's RTT-estimated `offsets` (wall anchors as the
    fallback — those trust each host's wall clock verbatim), and write
    one merged Chrome trace carrying the health verdict. Each rank's
    summary records the alignment actually applied as ``skew_ns``, so
    incident tooling (scripts/incident_report.py) can re-order
    cross-host events on one timebase. Lifecycle events riding the
    flight dumps land as instant markers in the merged trace. Returns
    the output path, or None if no dumps appeared."""
    deadline = time.monotonic() + max(grace_s, 0.0)
    paths: List[str] = []
    while True:
        paths = sorted(glob.glob(
            os.path.join(trace_dir, f"{FLIGHT_PREFIX}*.json")))
        if expect_ranks is not None and len(paths) >= expect_ranks:
            break
        if time.monotonic() >= deadline:
            break
        time.sleep(0.05)
    if not paths:
        return None
    segments = []
    local_anchor = None
    docs = []
    for p in paths:
        try:
            with open(p) as f:
                docs.append(json.load(f))
        except (OSError, ValueError):
            continue
    for d in docs:
        if d.get("rank") == 0:
            local_anchor = d.get("anchor")
    if local_anchor is None and docs:
        local_anchor = docs[0].get("anchor")
    offsets = offsets or {}
    for d in docs:
        anchor = d.get("anchor")
        r = int(d.get("rank", -1))
        off = offsets.get(r)
        if off is None:
            off = wall_anchor_offset(anchor, local_anchor)
        segments.append({
            "rank": r,
            "events": d.get("events", []),
            "anchor": anchor,
            "offset_ns": int(off),
        })
    skew_by_rank = {s["rank"]: s["offset_ns"] for s in segments}
    base = (local_anchor or {}).get("mono_anchor_ns", 0)
    doc = render_chrome(segments, base_ns=base, metadata={
        "horovod_postmortem": {
            "verdict": verdict,
            "health": health,
            "ranks": sorted(s["rank"] for s in segments),
            "per_rank": {
                str(d.get("rank")): {
                    "reason": d.get("reason", ""),
                    "events": len(d.get("events", [])),
                    "dropped": d.get("dropped", 0),
                    # Health plane (docs/health.md): the flight dump
                    # carries the rank's scalar time-series and latched
                    # alerts; the summary counts them so a reader knows
                    # which flight file holds history worth opening.
                    "timeseries_samples": len(
                        (d.get("timeseries") or {}).get("samples", [])),
                    "alerts_firing": (d.get("alerts") or {}).get(
                        "firing", []),
                    # Goodput ledger (docs/goodput.md): how much of the
                    # job had become training when it died — the badput
                    # breakdown rides the flight dump itself.
                    "goodput_ratio": ((d.get("goodput") or {})
                                      .get("goodput") or {}).get("ratio"),
                    "goodput_steps": ((d.get("goodput") or {})
                                      .get("steps") or {}).get("total"),
                    # Clock alignment actually applied to this rank's
                    # lane (peer mono clock minus the coordinator's,
                    # ns): RTT-estimated when the health plane had a
                    # sample, wall-anchor fallback otherwise.
                    "skew_ns": skew_by_rank.get(int(d.get("rank", -1)), 0),
                    "lifecycle_events": len(d.get("lifecycle") or []),
                } for d in docs
            },
        },
    })
    # Lifecycle markers (docs/events.md): each dump's events-plane tail
    # becomes instant events on that rank's lane, so a re-mesh / drain /
    # swap reads inline with the spans around it.
    from ..utils import chrome_trace

    for d in docs:
        r = int(d.get("rank", -1))
        off = skew_by_rank.get(r, 0)
        for led in d.get("lifecycle") or []:
            try:
                ts_us = (int(led["mono_ns"]) - off - base) / 1e3
            except (KeyError, TypeError, ValueError):
                continue
            doc["traceEvents"].append(chrome_trace.instant(
                str(led.get("kind", "event")), ts_us, pid=r,
                cat="lifecycle",
                args={k: v for k, v in led.items() if k != "mono_ns"}))
    out = os.path.join(trace_dir, out_name)
    atomic_file.atomic_write(out, lambda f: json.dump(doc, f), mode="w")
    return out
