"""On-box metrics time-series: a bounded ring of periodic registry
samples with derived series computed at read time (docs/health.md).

Every observability layer before this one (PR 2 counters, PR 6 spans,
the serving histograms) is point-in-time: a scrape answers "how much so
far", never "how fast right now" or "is this getting worse". This
module is the history: a daemon sampler thread snapshots the telemetry
registry every ``HOROVOD_METRICS_SAMPLE_SECONDS`` into a fixed-capacity
ring (``HOROVOD_METRICS_HISTORY_SAMPLES`` entries — bounded memory like
the PR 6 flight-recorder ring, overwrites counted), and everything
interesting is DERIVED at read time, never at sample time:

* **counter rates** — delta/sec over a window, summing consecutive
  positive deltas so a counter reset (engine re-init during an elastic
  reset) contributes the post-reset value instead of a huge negative
  spike (the Prometheus ``rate()`` reset rule);
* **windowed histogram quantiles** — the registry's log2 buckets make
  a within-window p50/p99 one subtraction per bucket: cumulative-walk
  the bucket-count deltas between the window edges and interpolate
  inside the crossing bucket;
* **gauge windows** — min/max/last over the window.

Sampling reuses ``MetricsRegistry.snapshot()`` (the machinery the
exporters already use), so the data-plane hot path pays nothing — the
only cost is one snapshot per cadence tick on a daemon thread. The
ring feeds the ``/timeseries`` view, the alert engine
(common/alerts.py, evaluated on each sample tick), and the failure
post-mortem (engine/engine.py dumps the scalar series next to the
flight recorder, so a failure report carries the last N minutes of
every key series, not just spans).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

# One sample: (wall clock s, monotonic s, full registry snapshot).
Sample = Tuple[float, float, dict]


# ---------------------------------------------------------------------------
# Derived-series math (pure functions over sample lists; unit-testable
# on synthetic data without threads or clocks).

def counter_rate(samples: List[Sample], key: str,
                 window_s: float, now: Optional[float] = None
                 ) -> Optional[float]:
    """Per-second rate of a counter over the trailing window.

    Consecutive-pair deltas are summed with the Prometheus reset rule:
    a sample smaller than its predecessor means the counter restarted
    (elastic re-init), so that pair contributes the post-reset value —
    never a negative delta. None when fewer than two in-window samples
    exist (no rate is better than a made-up one)."""
    win = _in_window(samples, window_s, now)
    pts = [(mono, snap.get(key)) for _, mono, snap in win
           if isinstance(snap.get(key), (int, float))]
    if len(pts) < 2:
        return None
    total = 0.0
    for (_, prev), (_, cur) in zip(pts, pts[1:]):
        total += cur - prev if cur >= prev else cur
    dt = pts[-1][0] - pts[0][0]
    if dt <= 0:
        return None
    return total / dt


def histogram_window(samples: List[Sample], key: str,
                     window_s: float, now: Optional[float] = None
                     ) -> Optional[dict]:
    """Bucket-count deltas of a histogram across the trailing window:
    ``{count, sum, bounds, counts}`` shaped exactly like a registry
    histogram snapshot, but covering only observations inside the
    window. Both edges honor `now`: the upper edge is the newest
    sample at-or-before `now` (so a trailing-baseline window ending in
    the past never absorbs newer observations), the base the newest
    sample at-or-before `now - window_s` (zeros when history is
    younger than the window — then the "window" is process lifetime).
    A reset (any bucket shrank) falls back to the upper sample's
    absolute counts: everything it holds happened after the restart."""
    if not samples:
        return None
    now = samples[-1][1] if now is None else now
    cur = None
    cur_idx = -1
    for i in range(len(samples) - 1, -1, -1):
        if samples[i][1] <= now:
            cand = samples[i][2].get(key)
            if isinstance(cand, dict) and "counts" in cand:
                cur = cand
                cur_idx = i
            break
    if cur is None:
        return None
    base = None
    for i in range(cur_idx - 1, -1, -1):
        if samples[i][1] <= now - window_s:
            cand = samples[i][2].get(key)
            if isinstance(cand, dict) and "counts" in cand:
                base = cand
            break
    if base is not None and (
            len(base["counts"]) != len(cur["counts"])
            or any(c < b for c, b in zip(cur["counts"], base["counts"]))):
        base = None  # reset (or re-registered shape): delta from zero
    if base is None:
        counts = list(cur["counts"])
        count = cur.get("count", sum(counts))
        hsum = cur.get("sum", 0.0)
    else:
        counts = [c - b for c, b in zip(cur["counts"], base["counts"])]
        count = cur.get("count", 0) - base.get("count", 0)
        hsum = cur.get("sum", 0.0) - base.get("sum", 0.0)
    return {"count": count, "sum": hsum,
            "bounds": list(cur["bounds"]), "counts": counts}


def quantile_from_counts(bounds: List[float], counts: List[int],
                         q: float) -> Optional[float]:
    """Quantile from log2 bucket counts (the last entry is +Inf):
    cumulative walk, linear interpolation inside the crossing bucket.
    The +Inf bucket reports the highest finite bound (the
    histogram_quantile convention — no upper edge to interpolate to).
    None on an empty window."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            if i >= len(bounds):  # +Inf bucket
                return float(bounds[-1])
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            return lo + (hi - lo) * (target - cum) / c
        cum += c
    return float(bounds[-1])


def window_quantile(samples: List[Sample], key: str, q: float,
                    window_s: float, now: Optional[float] = None
                    ) -> Optional[float]:
    """p-q of a histogram's observations inside the trailing window."""
    w = histogram_window(samples, key, window_s, now)
    if w is None:
        return None
    return quantile_from_counts(w["bounds"], w["counts"], q)


def gauge_window(samples: List[Sample], key: str,
                 window_s: float, now: Optional[float] = None
                 ) -> Optional[dict]:
    """min/max/last of a scalar series over the trailing window."""
    vals = [snap.get(key) for _, _, snap in _in_window(samples, window_s, now)
            if isinstance(snap.get(key), (int, float))]
    vals = [v for v in vals if v == v]  # drop NaN (dead pull gauges)
    if not vals:
        return None
    return {"min": min(vals), "max": max(vals), "last": vals[-1],
            "count": len(vals)}


def family_items(snapshot: dict, name: str) -> Dict[str, object]:
    """All series of one metric family: the bare key plus every labeled
    ``name{...}`` variant (how alert rules scan per-peer gauges)."""
    prefix = name + "{"
    return {k: v for k, v in snapshot.items()
            if k == name or k.startswith(prefix)}


def _in_window(samples: List[Sample], window_s: float,
               now: Optional[float]) -> List[Sample]:
    if not samples:
        return []
    now = samples[-1][1] if now is None else now
    lo = now - window_s
    return [s for s in samples if s[1] >= lo]


def flatten_scalars(snapshot: dict) -> Dict[str, float]:
    """Scalar view of one snapshot for compact dumps: counters/gauges
    verbatim, histograms as ``_count``/``_sum`` (the telemetry
    ``scalars()`` convention)."""
    out: Dict[str, float] = {}
    for k, v in snapshot.items():
        if isinstance(v, dict):
            out[f"{k}_count"] = v.get("count", 0)
            out[f"{k}_sum"] = v.get("sum", 0.0)
        elif isinstance(v, (int, float)) and v == v:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# The store

class TimeSeriesStore:
    """Fixed-capacity ring of registry samples plus the derived-series
    accessors. Appends are O(1) (deque with maxlen); overwrites of
    never-dumped history are counted — a truncated post-mortem series
    must not read as the whole story (the SpanRecorder contract)."""

    def __init__(self, capacity: int, registry=None):
        self.capacity = max(int(capacity), 0)
        self._buf: deque = deque(maxlen=self.capacity or 1)
        self._total = 0
        self._lock = threading.Lock()
        self._m_samples = None
        self._m_dropped = None
        if registry is not None and self.capacity:
            self._m_samples = registry.counter(
                "horovod_timeseries_samples_total",
                "Registry snapshots taken by the on-box sampler")
            self._m_dropped = registry.counter(
                "horovod_timeseries_samples_dropped_total",
                "Sampler ring overwrites (history lost to the bounded "
                "ring before any dump)")

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def add_sample(self, snapshot: dict, wall: Optional[float] = None,
                   mono: Optional[float] = None):
        if not self.capacity:
            return
        with self._lock:
            dropped = len(self._buf) == self.capacity
            self._buf.append((
                time.time() if wall is None else wall,
                time.monotonic() if mono is None else mono,
                snapshot,
            ))
            self._total += 1
        if self._m_samples is not None:
            self._m_samples.inc()
        if dropped and self._m_dropped is not None:
            self._m_dropped.inc()

    def samples(self, window_s: Optional[float] = None) -> List[Sample]:
        with self._lock:
            out = list(self._buf)
        if window_s is not None:
            out = _in_window(out, window_s, None)
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(self._total - len(self._buf), 0)

    def last_age(self) -> float:
        """Seconds since the newest sample; -1 before the first (the
        alert engine's staleness guard reads this)."""
        with self._lock:
            if not self._buf:
                return -1.0
            return max(time.monotonic() - self._buf[-1][1], 0.0)

    # -- derived accessors ---------------------------------------------
    def rate(self, key: str, window_s: float) -> Optional[float]:
        return counter_rate(self.samples(), key, window_s)

    def quantile(self, key: str, q: float, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        return window_quantile(self.samples(), key, q, window_s, now)

    def hist_window(self, key: str, window_s: float,
                    now: Optional[float] = None) -> Optional[dict]:
        return histogram_window(self.samples(), key, window_s, now)

    def window(self, key: str, window_s: float) -> Optional[dict]:
        return gauge_window(self.samples(), key, window_s)

    def latest(self) -> Optional[dict]:
        with self._lock:
            return self._buf[-1][2] if self._buf else None

    def series(self, key: str, window_s: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """[(wall_s, value)] points of one scalar series."""
        return [(wall, snap[key]) for wall, _, snap
                in self.samples(window_s)
                if isinstance(snap.get(key), (int, float))]

    # -- rendering ------------------------------------------------------
    def view(self, window_s: float = 300.0,
             max_points: int = 120) -> dict:
        """The /timeseries body: ring state, a derived table for every
        series (counter rate, histogram windowed p50/p99, gauge
        min/max/last), and raw scalar points capped at `max_points`
        (newest kept)."""
        samples = self.samples()
        derived: Dict[str, dict] = {}
        points: Dict[str, list] = {}
        latest = samples[-1][2] if samples else {}
        for key, val in sorted(latest.items()):
            if isinstance(val, dict):
                w = histogram_window(samples, key, window_s)
                if w is None:
                    continue
                derived[key] = {
                    "kind": "histogram",
                    "window_count": w["count"],
                    "p50": quantile_from_counts(
                        w["bounds"], w["counts"], 0.5),
                    "p99": quantile_from_counts(
                        w["bounds"], w["counts"], 0.99),
                }
            elif isinstance(val, (int, float)):
                rate = counter_rate(samples, key, window_s)
                gw = gauge_window(samples, key, window_s)
                d = {"kind": "scalar", "last": val}
                if rate is not None:
                    d["rate_per_s"] = rate
                if gw is not None:
                    d["min"], d["max"] = gw["min"], gw["max"]
                derived[key] = d
                pts = [(round(wall, 3), snap[key])
                       for wall, _, snap in samples
                       if isinstance(snap.get(key), (int, float))]
                points[key] = pts[-max_points:]
        return {
            "capacity": self.capacity,
            "depth": len(samples),
            "dropped": self.dropped,
            "window_seconds": window_s,
            "derived": derived,
            "points": points,
        }

    def dump_scalars(self, max_samples: int = 120) -> dict:
        """Compact scalar history for the post-mortem: the newest
        `max_samples` samples, histograms flattened to _count/_sum."""
        samples = self.samples()[-max_samples:]
        return {
            "capacity": self.capacity,
            "dropped": self.dropped,
            "samples": [
                [round(wall, 3), flatten_scalars(snap)]
                for wall, _, snap in samples
            ],
        }


# ---------------------------------------------------------------------------
# The sampler thread

class MetricsSampler:
    """Daemon thread snapshotting `registry` into a TimeSeriesStore
    every `interval` seconds, with tick callbacks (the alert engine
    registers one) invoked after each sample lands. One per engine,
    like the registry itself — the in-process multi-rank harness keeps
    per-"rank" history separable."""

    def __init__(self, registry, capacity: Optional[int] = None,
                 interval: Optional[float] = None):
        if capacity is None:
            capacity = env_cfg.metrics_history_samples()
        if interval is None:
            interval = env_cfg.metrics_sample_seconds()
        self.registry = registry
        self.interval = interval
        self.store = TimeSeriesStore(
            capacity if interval > 0 else 0, registry=registry)
        self._callbacks: List[Callable[[TimeSeriesStore], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.store.enabled and self.interval > 0

    def add_tick_callback(self, fn: Callable[[TimeSeriesStore], None]):
        self._callbacks.append(fn)

    def sample_once(self):
        if not self.store.enabled:
            return
        try:
            snap = self.registry.snapshot()
        except Exception:  # a broken pull gauge must not kill the loop
            logger.exception("metrics sample failed")
            return
        self.store.add_sample(snap)
        for fn in list(self._callbacks):
            try:
                fn(self.store)
            except Exception:
                logger.exception("sampler tick callback failed")

    def start(self) -> "MetricsSampler":
        if not self.enabled or self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="hvd-metrics-sampler", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        # First sample immediately: short-lived jobs (and smokes) get a
        # baseline before the first full interval elapses.
        self.sample_once()
        while not self._stop.wait(self.interval):
            self.sample_once()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def status(self) -> dict:
        return {
            "enabled": self.enabled,
            "interval_seconds": self.interval,
            "capacity": self.store.capacity,
            "depth": self.store.depth(),
            "dropped": self.store.dropped,
            "last_sample_age_seconds": round(self.store.last_age(), 3),
        }
