"""Durability plane: async sharded checkpoints with kill-all-job
recovery (docs/checkpoint.md).

The elastic layer (PAPER.md L3/L5: ``State.save/restore/sync`` +
commit hooks) snapshots state **in memory** — it survives losing a
rank, not losing the job. This module is the missing half of the
fault-tolerance story (ROADMAP item 5): every K commits each rank
streams a shard of the committed state to shared storage off the
training thread, the coordinator two-phase-commits a manifest once
every shard is durable, and a restarted job — even after *all* ranks
died — resumes from the newest complete checkpoint before its first
step.

The moving parts:

* **Copy-on-write snapshot** — ``state.commit()`` already host-copies
  pytrees (``np.asarray`` in ``elastic/state.py:save``) and deep-copies
  scalars into ``_saved``/``_saved_trees``; a checkpoint *references*
  those arrays (``State.checkpoint_trees/objects``). ``save()`` rebinds
  — never mutates — the snapshot dicts, so the background writer reads
  a stable snapshot while training races ahead.

* **Sharded background writes** — the flattened leaf list is cut into
  per-rank contiguous ranges balanced by bytes (`shard_ranges`; every
  rank computes the same cut from the replicated state). Each rank's
  writer thread pickles its range and lands it crash-safe
  (tmp+rename+fsync via ``utils/atomic_file.py`` — the protocol proven
  in ``spark/store.py``), then a ``.meta.json`` sidecar, then acks.

* **Two-phase manifest commit** — the durability ack (shard byte count
  + CRC32) travels to the coordinator over the rendezvous KV (the same
  control plane carrying PR 5's health verdicts; scope
  ``ckpt_ack_s<step>``), with a filesystem fallback (the sidecars) when
  no KV is configured. Only after **every** rank of the writing world
  acks does the coordinator atomically write ``manifest-<step>.json``
  and publish ``ckpt/latest`` to the KV. A manifest therefore never
  references a missing shard; a crash at any point leaves either the
  previous complete checkpoint or the new one discoverable — never a
  torn one.

* **Restore with re-sharding** — discovery walks manifests newest-first
  and takes the first whose shards all exist with the recorded sizes
  (torn/partial attempts are skipped; ``*.tmp.*`` debris is invisible
  by construction). Every rank loads all shards, verifies CRCs,
  reassembles the leaf list by the manifest's shard-range metadata and
  unflattens against the live state's structure — so a job restarted at
  a *different* world size restores bit-identically and simply re-cuts
  its own shards at the next checkpoint.

* **GC** — after each commit the coordinator keeps the newest
  ``HOROVOD_CHECKPOINT_KEEP`` complete checkpoints and removes older
  manifests (manifest first, then shards — crash-ordering keeps
  discovery sound), orphaned shard dirs from abandoned commits, and
  stray tmp debris.

Instrumented end to end: ``horovod_checkpoint_{writes,bytes,failures,
skipped,commits,restores}_total``, write/commit latency histograms, a
``checkpoint`` view on ``/status`` (engine/engine.py), and
``ckpt.snapshot``/``ckpt.write``/``ckpt.commit`` tracing spans. Chaos
rules ``diskfail:``/``diskslow:`` (common/fault_injection.py) target
exactly this I/O.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..utils import atomic_file
from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

FORMAT_VERSION = 1
MANIFEST_PREFIX = "manifest-"
STEP_DIR_PREFIX = "ckpt-"
ACK_SCOPE_PREFIX = "ckpt_ack_s"
LATEST_SCOPE = "ckpt"
LATEST_KEY = "latest"
RESUME_KEY = "resume"

CAT_CKPT = "ckpt"


# ---------------------------------------------------------------------------
# Layout + manifest discovery (module-level: the restore side must work
# with no manager — the driver peeks at resume state, the smoke harness
# verifies parity).

def step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"{STEP_DIR_PREFIX}{step:010d}")


def shard_file(step: int, rank: int) -> str:
    """Manifest-relative shard path."""
    return f"{STEP_DIR_PREFIX}{step:010d}/shard-{rank:05d}.pkl"


def manifest_path(root: str, step: int) -> str:
    return os.path.join(root, f"{MANIFEST_PREFIX}{step:010d}.json")


def list_manifests(root: str) -> List[Tuple[int, str]]:
    """(step, path) for every manifest file, oldest first. Torn writes
    never appear: manifests land by atomic rename and tmp names don't
    match the ``manifest-*.json`` shape."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return []
    for n in names:
        if not (n.startswith(MANIFEST_PREFIX) and n.endswith(".json")):
            continue
        if atomic_file.is_tmp_debris(n):
            continue
        try:
            out.append((int(n[len(MANIFEST_PREFIX):-len(".json")]),
                        os.path.join(root, n)))
        except ValueError:
            continue
    out.sort()
    return out


def load_manifest(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_complete(root: str, manifest: dict) -> bool:
    """Every shard the manifest references exists with the recorded
    size. (The commit protocol makes this an invariant; the check keeps
    discovery sound against half-GC'd or hand-damaged directories.)"""
    for sh in manifest.get("shards", []):
        p = os.path.join(root, sh["file"])
        try:
            if os.path.getsize(p) != sh["bytes"]:
                return False
        except OSError:
            return False
    return True


def find_latest_manifest(root: str
                         ) -> Optional[Tuple[int, dict, str]]:
    """Newest *complete* checkpoint: (step, manifest, manifest_path).
    Walks newest-first so a torn or half-GC'd newer attempt falls back
    to the last good one instead of failing the restore."""
    for step, path in reversed(list_manifests(root)):
        man = load_manifest(path)
        if man is None or man.get("format") != FORMAT_VERSION:
            continue
        if is_complete(root, man):
            return step, man, path
    return None


def load_checkpoint_arrays(root: str, manifest: dict, verify: bool = True
                           ) -> Tuple[dict, Dict[str, list]]:
    """Read every shard of a manifest and reassemble
    ``(objects, {attr: leaves})`` — the full replicated state,
    independent of how many ranks wrote it. CRC-verifies each shard
    (unless ``verify=False``) and checks the shard ranges tile the
    manifest's leaf count exactly."""
    shards = sorted(manifest["shards"], key=lambda s: s["leaves"][0])
    leaves: List = []
    objects: dict = {}
    cursor = 0
    for sh in shards:
        payload = atomic_file.checked_read_bytes(
            os.path.join(root, sh["file"]))
        if verify and zlib.crc32(payload) != sh["crc32"]:
            raise ValueError(
                f"checkpoint shard {sh['file']} failed CRC verification")
        doc = pickle.loads(payload)
        lo, hi = doc["leaf_range"]
        if lo != cursor:
            raise ValueError(
                f"checkpoint shard ranges do not tile: expected leaf "
                f"{cursor}, shard {sh['file']} starts at {lo}")
        cursor = hi
        leaves.extend(doc["leaves"])
        if doc.get("objects") is not None:
            objects = doc["objects"]
    if cursor != manifest["num_leaves"]:
        raise ValueError(
            f"checkpoint covers {cursor} leaves, manifest says "
            f"{manifest['num_leaves']}")
    trees: Dict[str, list] = {}
    i = 0
    for attr in manifest["attrs"]:
        n = manifest["attr_counts"][attr]
        trees[attr] = leaves[i:i + n]
        i += n
    return objects, trees


def _sweep_debris(root: str, keep) -> None:
    """Shared directory sweep (GC + purge): root-level ``*.tmp.*``
    debris always goes; a ``ckpt-<step>`` dir goes unless
    ``keep(step)``."""
    try:
        names = os.listdir(root)
    except OSError:
        return
    for name in names:
        full = os.path.join(root, name)
        if atomic_file.is_tmp_debris(name) and os.path.isfile(full):
            try:
                os.unlink(full)
            except OSError:
                pass
            continue
        if not (name.startswith(STEP_DIR_PREFIX) and os.path.isdir(full)):
            continue
        try:
            s = int(name[len(STEP_DIR_PREFIX):])
        except ValueError:
            continue
        if not keep(s):
            shutil.rmtree(full, ignore_errors=True)


def purge_newer_than(root: str, step: Optional[int]):
    """Disarm attempt debris NEWER than `step` (every step when
    ``step`` is None). Called after a restore point is chosen (restore,
    elastic resync): a manifest-less shard dir above the floor is an
    aborted commit and goes entirely, and any OTHER above-floor dir
    sheds its ``.meta.json`` sidecars. The sweep matters beyond
    tidiness: a sidecar is a durability ack, and when a restarted (or
    reset) trajectory re-reaches the same step number, a pre-crash ack
    would satisfy the commit barrier with bytes the current run never
    wrote.

    Anything WITH a manifest is deliberately kept, whatever the floor:
    a complete manifest that lands concurrently (a live coordinator's
    commit racing a joining worker's restore) is a real checkpoint,
    not debris, and even an incomplete or format-mismatched one is
    forensic data a newer binary or an operator may want — discovery
    skips it either way, and with its sidecars gone it cannot poison
    a commit barrier. Every rank calls this with the same
    deterministically-chosen floor, so concurrent sweeps are
    idempotent."""
    floor = -1 if step is None else step
    manifested = {s for s, _ in list_manifests(root)}
    _sweep_debris(root, keep=lambda s: s <= floor or s in manifested)
    for s in manifested:
        if s <= floor:
            continue
        d = step_dir(root, s)
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            if name.endswith(".meta.json"):
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass


def shard_ranges(leaf_bytes: List[int], nshards: int
                 ) -> List[Tuple[int, int]]:
    """Cut ``len(leaf_bytes)`` leaves into ``nshards`` contiguous ranges
    balanced by bytes. Deterministic given the byte sizes — every rank
    computes the same cut from its replicated snapshot, so no cut needs
    to travel. Ranges may be empty when there are more ranks than
    leaves (the empty shard still gets written and acked: the commit
    barrier stays uniform). Also the ownership rule for ZeRO optimizer
    state (optim/zero.py): the eager plane feeds it equal-weight
    512-element blocks of the flat state buffer, so checkpoint shards
    and optimizer shards are cut by one deterministic function."""
    total = sum(leaf_bytes)
    n = len(leaf_bytes)
    cuts = [0]
    acc = 0
    idx = 0
    for k in range(1, nshards):
        boundary = total * k / nshards
        while idx < n and acc + leaf_bytes[idx] <= boundary:
            acc += leaf_bytes[idx]
            idx += 1
        cuts.append(idx)
    cuts.append(n)
    return [(cuts[i], cuts[i + 1]) for i in range(nshards)]


# ---------------------------------------------------------------------------
# Snapshot: what one checkpoint write carries.

class _Snapshot:
    __slots__ = ("step", "rank", "size", "objects", "trees", "attrs",
                 "leaves", "leaf_bytes", "done", "committed")

    def __init__(self, step: int, rank: int, size: int, objects: dict,
                 trees: Dict[str, list]):
        self.step = step
        self.rank = rank
        self.size = size
        self.objects = objects
        # Deterministic attr order: the manifest's leaf layout must be
        # identical on every rank.
        self.attrs = sorted(trees)
        self.trees = trees
        self.leaves = [leaf for a in self.attrs for leaf in trees[a]]
        self.leaf_bytes = [getattr(x, "nbytes", 64) for x in self.leaves]
        self.done = threading.Event()
        self.committed = False


# ---------------------------------------------------------------------------
# The manager

class CheckpointManager:
    """Per-rank durability agent: snapshot at commit, write this rank's
    shard in the background, two-phase-commit the manifest on the
    coordinator, GC, restore. One instance per rank; all instances
    share ``directory`` (shared storage)."""

    def __init__(self, directory: str, rank: int = 0, size: int = 1,
                 interval_steps: Optional[int] = None,
                 keep: Optional[int] = None,
                 commit_timeout: Optional[float] = None,
                 rendezvous=None, registry=None, tracer=None,
                 fsync: Optional[bool] = None):
        self.directory = os.path.abspath(directory)
        self.rank = rank
        self.size = size
        self.interval_steps = (env_cfg.checkpoint_interval_steps()
                               if interval_steps is None else interval_steps)
        self.keep = env_cfg.checkpoint_keep() if keep is None else max(keep, 1)
        self.commit_timeout = (env_cfg.checkpoint_commit_timeout()
                               if commit_timeout is None else commit_timeout)
        self.fsync = env_cfg.checkpoint_fsync() if fsync is None else fsync
        self.rendezvous = rendezvous
        self._tracer = tracer
        if registry is None:
            from . import telemetry

            registry = telemetry.default_registry()
        self._m_writes = registry.counter(
            "horovod_checkpoint_writes_total",
            "Checkpoint shards durably written by this rank")
        self._m_bytes = registry.counter(
            "horovod_checkpoint_bytes_total",
            "Serialized checkpoint shard bytes written by this rank")
        self._m_failures = registry.counter(
            "horovod_checkpoint_failures_total",
            "Checkpoint shard writes or manifest commits that failed "
            "(a failed checkpoint is skipped — training never blocks, "
            "and no manifest ever references a missing shard)")
        self._m_skipped = registry.counter(
            "horovod_checkpoint_skipped_total",
            "Checkpoint snapshots skipped because the previous shard "
            "write was still in flight (writer backpressure)")
        self._m_commits = registry.counter(
            "horovod_checkpoint_commits_total",
            "Manifests two-phase-committed by the coordinator")
        self._m_restores = registry.counter(
            "horovod_checkpoint_restores_total",
            "States restored from a committed checkpoint")
        self._m_write_s = registry.histogram(
            "horovod_checkpoint_write_seconds",
            "Background shard serialize+write+ack latency")
        self._m_commit_s = registry.histogram(
            "horovod_checkpoint_commit_seconds",
            "Coordinator ack-collection + manifest commit latency")
        self._m_last_step = registry.gauge(
            "horovod_checkpoint_last_step",
            "Step of the last successfully committed checkpoint")
        self._commit_count = 0
        self._last_committed_step: Optional[int] = None
        self._last_write_step: Optional[int] = None
        self._last_error: Optional[str] = None
        self._pending: Optional[_Snapshot] = None
        self._cancel_commit = threading.Event()
        self._deferred_purge_floor: Optional[int] = None
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        os.makedirs(self.directory, exist_ok=True)

    # -- plumbing ------------------------------------------------------
    def tracer(self):
        if self._tracer is not None:
            return self._tracer
        # Lazy: ride the engine's flight recorder when one is live, so
        # ckpt.* spans land next to the collectives they overlap with.
        from . import basics, tracing

        eng = basics._state.engine
        if eng is not None and getattr(eng, "tracer", None) is not None:
            return eng.tracer
        return tracing.NULL_TRACER

    def _world(self) -> Tuple[int, int]:
        """Current (rank, size): re-read from the live runtime so an
        elastic reset (world grew/shrank) re-cuts shards correctly."""
        from . import basics

        if basics.is_initialized():
            return basics._state.rank, basics._state.size
        return self.rank, self.size

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._writer_loop, name="hvd-ckpt-writer",
                daemon=True)
            self._thread.start()

    # -- the commit-path API -------------------------------------------
    def maybe_save(self, state) -> bool:
        """Called once per ``state.commit()``. Snapshots + enqueues a
        checkpoint every ``interval_steps`` commits; returns whether one
        was enqueued. Never blocks on I/O: if the previous shard write
        is still in flight the snapshot is skipped (and counted)."""
        self._commit_count += 1
        if self.interval_steps <= 0:
            return False
        if self._commit_count % self.interval_steps != 0:
            return False
        return self.save(state, step=self._commit_count)

    def save(self, state, step: Optional[int] = None,
             blocking: bool = False, timeout: float = 300.0) -> bool:
        """Snapshot `state`'s last committed values and hand them to the
        background writer. With ``blocking=True`` waits until the shard
        is durable (and, on the coordinator, the manifest committed) —
        tests and final-checkpoint-at-exit use that; training loops
        never should."""
        from . import goodput

        if step is None:
            step = self._commit_count
        rank, size = self._world()
        t0 = time.perf_counter()
        with self.tracer().span("ckpt.snapshot", cat=CAT_CKPT,
                                args={"step": step}):
            snap = _Snapshot(step, rank, size,
                             state.checkpoint_objects(),
                             state.checkpoint_trees())
        # Goodput plane (docs/goodput.md): the snapshot reference copy
        # runs on the training thread — checkpoint-stall badput. The
        # background pickle+write overlaps and is deliberately NOT
        # counted here.
        goodput.note_ckpt_stall(time.perf_counter() - t0)
        with self._cond:
            if self._pending is not None:
                self._m_skipped.inc()
                goodput.note_ckpt_skip()
                logger.warning(
                    "checkpoint at step %d skipped: previous shard write "
                    "still in flight", step)
                return False
            self._pending = snap
            self._ensure_thread()
            self._cond.notify_all()
        if blocking:
            if not snap.done.wait(timeout):
                raise TimeoutError(
                    f"checkpoint write at step {step} did not finish in "
                    f"{timeout:.0f}s")
        return True

    def save_now(self, state, timeout: float = 60.0) -> bool:
        """Drain path (docs/fault_tolerance.md "Announced preemption"):
        make the CURRENT commit durable before the process exits. If
        this commit's interval checkpoint just went out the writer is
        merely drained (waiting for it IS the forced checkpoint);
        otherwise any in-flight write is drained first — so ``save``
        cannot hit its backpressure-skip path — and this commit is
        written blocking. Called at the same commit on every rank (the
        drain barrier guarantees that), so the coordinator's ack
        barrier fills and the manifest commits."""
        deadline = time.monotonic() + max(timeout, 1.0)

        def left() -> float:
            return max(0.5, deadline - time.monotonic())

        if (self.interval_steps > 0 and self._commit_count > 0
                and self._commit_count % self.interval_steps == 0):
            return self.flush(timeout=left())
        if not self.flush(timeout=left()):
            return False
        return self.save(state, step=self._commit_count, blocking=True,
                         timeout=left())

    def resync_after_reset(self, flush_timeout: float = 30.0):
        """Re-anchor the interval counter after an elastic reset. The
        counter is per-rank private state: a worker that joined mid-run
        anchored at the restored step (or zero) while survivors kept
        counting, and drifted counters mean ranks snapshot on
        *different* commits — the coordinator's ack barrier then never
        fills and no manifest ever commits again. The newest complete
        manifest on shared storage is a value every rank reads
        identically, so re-anchoring there puts the counters back in
        lockstep. (A commit racing the reset can skew one reader by an
        interval; the mismatch surfaces as a counted, logged abandoned
        commit and heals at the next reset — never as corruption.)"""
        # A coordinator mid-commit is polling for acks that will never
        # come (the world that was writing them is gone): abandon —
        # and clean the attempt up — now, instead of wedging the reset
        # for commit_timeout.
        self._cancel_commit.set()
        try:
            drained = self.flush(timeout=flush_timeout)
        finally:
            self._cancel_commit.clear()
        found = find_latest_manifest(self.directory)
        anchor = found[0] if found is not None else 0
        # Sweep aborted-attempt debris above the anchor. Each rank
        # sweeps only after draining its OWN writer, so every sidecar
        # ack is removed by the rank that wrote it. If the writer is
        # STILL busy past the flush bound (a pathologically slow
        # store), sweeping now would race the late write's deposit —
        # defer the sweep to the writer thread itself, which runs it
        # right after that write lands.
        if drained:
            purge_newer_than(self.directory, anchor)
        else:
            logger.warning(
                "checkpoint writer still busy after %.0fs at reset; "
                "deferring the debris sweep until its write lands",
                flush_timeout)
            with self._cond:
                self._deferred_purge_floor = anchor
        self._commit_count = anchor

    def flush(self, timeout: float = 300.0) -> bool:
        """Wait for any in-flight checkpoint write to finish. Returns
        whether the writer is drained (False = still busy at the
        bound)."""
        with self._cond:
            snap = self._pending
        if snap is not None:
            return snap.done.wait(timeout)
        return True

    def stop(self, timeout: float = 30.0):
        """Drain the writer thread. In-flight work completes (the last
        checkpoint of a clean shutdown matters most)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    # -- writer thread -------------------------------------------------
    def _writer_loop(self):
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                snap = self._pending
                if snap is None:
                    return  # stopped with nothing pending
            try:
                self._write_shard(snap)
            except Exception:
                # Checkpointing must never kill training; the failure
                # is counted and the next interval tries again.
                self._m_failures.inc()
                logger.exception("checkpoint write at step %d failed",
                                 snap.step)
            finally:
                with self._cond:
                    self._pending = None
                    deferred = self._deferred_purge_floor
                    self._deferred_purge_floor = None
                    self._cond.notify_all()
                if deferred is not None:
                    # A reset's sweep found this writer still busy and
                    # handed it over: now that the late write landed,
                    # disarm its (stale, above-anchor) sidecars.
                    try:
                        purge_newer_than(self.directory, deferred)
                    except OSError:  # pragma: no cover - best effort
                        pass
                snap.done.set()
            if self._stop:
                return

    def _shard_doc(self, snap: _Snapshot, lo: int, hi: int) -> dict:
        return {
            "format": FORMAT_VERSION,
            "step": snap.step,
            "rank": snap.rank,
            "world_size": snap.size,
            "leaf_range": (lo, hi),
            "leaves": snap.leaves[lo:hi],
            # Scalars ride rank 0's shard — small, and exactly one copy.
            "objects": snap.objects if snap.rank == 0 else None,
            "attrs": snap.attrs,
            "attr_counts": {a: len(snap.trees[a]) for a in snap.attrs},
        }

    def _write_shard(self, snap: _Snapshot):
        t0 = time.perf_counter()
        lo, hi = shard_ranges(snap.leaf_bytes, snap.size)[snap.rank]
        rel = shard_file(snap.step, snap.rank)
        path = os.path.join(self.directory, rel)
        try:
            with self.tracer().span(
                    "ckpt.write", cat=CAT_CKPT,
                    args={"step": snap.step, "rank": snap.rank,
                          "leaves": hi - lo}):
                payload = pickle.dumps(self._shard_doc(snap, lo, hi),
                                       protocol=pickle.HIGHEST_PROTOCOL)
                crc = zlib.crc32(payload)
                atomic_file.atomic_write_bytes(path, payload,
                                               fsync=self.fsync)
                meta = {
                    "format": FORMAT_VERSION,
                    "step": snap.step,
                    "rank": snap.rank,
                    "world_size": snap.size,
                    "file": rel,
                    "leaves": [lo, hi],
                    "bytes": len(payload),
                    "crc32": crc,
                }
                # Durability ack, two transports: the sidecar (always —
                # the filesystem IS shared, restore depends on it) and
                # the rendezvous KV (when configured — the same control
                # plane carrying health verdicts; the coordinator sees
                # it without filesystem polling).
                atomic_file.atomic_write_text(
                    f"{path}.meta.json", json.dumps(meta),
                    fsync=self.fsync)
                if self.rendezvous is not None:
                    try:
                        self.rendezvous.put(
                            f"{ACK_SCOPE_PREFIX}{snap.step}",
                            str(snap.rank), json.dumps(meta).encode())
                    except Exception as e:  # KV down ≠ shard not durable
                        logger.warning(
                            "checkpoint ack via KV failed (%s); the "
                            "coordinator falls back to the sidecar", e)
        except OSError as e:
            self._m_failures.inc()
            self._last_error = f"step {snap.step}: {e}"
            logger.error(
                "checkpoint shard write at step %d failed: %s — no ack "
                "sent; the coordinator will not commit this checkpoint",
                snap.step, e)
            return
        self._m_writes.inc()
        self._m_bytes.inc(len(payload))
        self._m_write_s.observe(time.perf_counter() - t0)
        self._last_write_step = snap.step
        if snap.rank == 0:
            self._commit(snap)

    # -- coordinator: two-phase commit ---------------------------------
    def _ack_backed_by_shard(self, meta: dict) -> bool:
        """An ack counts only if the shard it describes is on disk at
        the recorded size. A stale KV ack from an earlier attempt at
        the same step (its file swept by the restore/reset purges)
        must keep the barrier waiting for a fresh write — never fill
        it with bytes from another trajectory."""
        try:
            return os.path.getsize(
                os.path.join(self.directory, meta["file"])
            ) == meta["bytes"]
        except (OSError, KeyError, TypeError):
            return False

    def _cleanup_attempt(self, step: int):
        """Remove an abandoned attempt's shards, sidecar acks, and KV
        acks, so nothing of it can satisfy a later re-attempt at the
        same step number with stale bytes."""
        shutil.rmtree(step_dir(self.directory, step), ignore_errors=True)
        if self.rendezvous is not None:
            try:
                self.rendezvous.delete(f"{ACK_SCOPE_PREFIX}{step}")
            except Exception:
                pass

    def _read_ack(self, step: int, rank: int) -> Optional[dict]:
        if self.rendezvous is not None:
            try:
                raw = self.rendezvous.get(f"{ACK_SCOPE_PREFIX}{step}",
                                          str(rank))
                if raw:
                    return json.loads(raw.decode())
            except Exception:
                pass  # fall through to the sidecar
        p = os.path.join(self.directory,
                         f"{shard_file(step, rank)}.meta.json")
        try:
            with open(p) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _commit(self, snap: _Snapshot):
        t0 = time.perf_counter()
        with self.tracer().span("ckpt.commit", cat=CAT_CKPT,
                                args={"step": snap.step,
                                      "world_size": snap.size}):
            deadline = time.monotonic() + self.commit_timeout
            acks: Dict[int, dict] = {}
            missing = set(range(snap.size))
            while missing:
                for r in sorted(missing):
                    meta = self._read_ack(snap.step, r)
                    if (meta is not None and meta.get("step") == snap.step
                            and self._ack_backed_by_shard(meta)):
                        acks[r] = meta
                missing -= set(acks)
                if not missing:
                    break
                # A pending deferred sweep means a reset moved on while
                # this write was in flight: its commit must not sit out
                # the full ack timeout against a world that is gone.
                cancelled = (self._cancel_commit.is_set()
                             or self._deferred_purge_floor is not None)
                if cancelled or time.monotonic() > deadline:
                    reason = (
                        "cancelled by elastic reset" if cancelled else
                        f"no durability ack from ranks {sorted(missing)} "
                        f"within {self.commit_timeout:.0f}s")
                    self._m_failures.inc()
                    self._last_error = f"step {snap.step}: {reason}"
                    logger.error(
                        "checkpoint commit at step %d abandoned: %s — "
                        "the previous committed checkpoint remains the "
                        "restore point", snap.step, reason)
                    self._cleanup_attempt(snap.step)
                    return
                time.sleep(0.02)
            manifest = {
                "format": FORMAT_VERSION,
                "step": snap.step,
                "time": time.time(),
                "world_size": snap.size,
                "num_leaves": len(snap.leaves),
                "attrs": snap.attrs,
                "attr_counts": {a: len(snap.trees[a]) for a in snap.attrs},
                "objects_shard": 0,
                "shards": [
                    {"rank": r, "file": acks[r]["file"],
                     "leaves": acks[r]["leaves"],
                     "bytes": acks[r]["bytes"], "crc32": acks[r]["crc32"]}
                    for r in range(snap.size)
                ],
            }
            try:
                atomic_file.atomic_write_text(
                    manifest_path(self.directory, snap.step),
                    json.dumps(manifest, indent=1, sort_keys=True),
                    fsync=self.fsync)
            except OSError as e:
                self._m_failures.inc()
                self._last_error = f"step {snap.step}: manifest: {e}"
                logger.error("checkpoint manifest commit at step %d "
                             "failed: %s", snap.step, e)
                self._cleanup_attempt(snap.step)
                return
            # Phase 2 is done the instant the manifest rename lands;
            # the KV publish is observability (driver /status, fleet
            # dashboards), not correctness.
            if self.rendezvous is not None:
                try:
                    self.rendezvous.put(
                        LATEST_SCOPE, LATEST_KEY,
                        json.dumps({"step": snap.step,
                                    "world_size": snap.size}).encode())
                except Exception:
                    pass
        snap.committed = True
        self._last_committed_step = snap.step
        self._m_commits.inc()
        self._m_last_step.set(snap.step)
        self._m_commit_s.observe(time.perf_counter() - t0)
        from . import events as events_mod

        events_mod.emit(events_mod.CKPT_COMMIT, ckpt_step=snap.step,
                        shards=snap.size)
        logger.info("checkpoint committed at step %d (%d shards)",
                    snap.step, snap.size)
        try:
            self._gc()
        except OSError as e:  # pragma: no cover - GC is best-effort
            logger.warning("checkpoint GC failed: %s", e)

    def _gc(self):
        """Keep the newest `keep` complete checkpoints; drop older
        manifests (manifest first, THEN shards — a crash between the
        two leaves an orphan dir, never a manifest with missing
        shards), abandoned-commit orphan dirs older than the newest
        committed checkpoint (a straggler rank still writing into one
        sees its rename fail — counted — rather than resurrect it),
        and root tmp debris."""
        manifests = list_manifests(self.directory)
        if not manifests:
            return
        newest_step = manifests[-1][0]
        kept = {s for s, _ in manifests[-self.keep:]}
        for s, path in manifests[:-self.keep]:
            try:
                os.unlink(path)
            except OSError:
                pass
            shutil.rmtree(step_dir(self.directory, s), ignore_errors=True)
        _sweep_debris(self.directory,
                      keep=lambda s: s in kept or s > newest_step)

    # -- restore -------------------------------------------------------
    def restore_latest(self, state) -> Optional[int]:
        """Load the newest complete checkpoint into `state` (then
        re-snapshot it, so a later in-memory ``restore()`` rolls back
        to the restored values). Walks damaged checkpoints newest-first
        — a corrupt shard falls back to the previous complete manifest.
        Returns the restored step, or None when nothing usable exists.
        The caller still runs ``state.sync()`` afterwards; restore is
        deterministic across ranks, so the broadcast is a no-op check,
        not a correctness crutch."""
        for step, path in reversed(list_manifests(self.directory)):
            man = load_manifest(path)
            if (man is None or man.get("format") != FORMAT_VERSION
                    or not is_complete(self.directory, man)):
                continue
            try:
                objects, trees = load_checkpoint_arrays(self.directory, man)
            except (OSError, ValueError, pickle.UnpicklingError) as e:
                self._m_failures.inc()
                logger.error(
                    "checkpoint at step %d unreadable (%s); falling back "
                    "to the previous complete checkpoint", step, e)
                continue
            state.load_checkpoint(objects, trees)
            self._commit_count = step
            self._last_committed_step = step
            self._m_restores.inc()
            self._m_last_step.set(step)
            # Sweep aborted-commit debris newer than the restore point
            # — crucially its .meta.json acks, which would otherwise
            # satisfy a repeated commit barrier at the same step with
            # pre-crash bytes.
            purge_newer_than(self.directory, step)
            from . import events as events_mod

            events_mod.emit(events_mod.CKPT_RESTORE, ckpt_step=step,
                            written_world=man["world_size"],
                            restore_world=self._world()[1])
            logger.info(
                "restored checkpoint step %d (written at world size %d, "
                "restoring at world size %d)", step, man["world_size"],
                self._world()[1])
            return step
        # Nothing restorable: every manifest/shard dir present is an
        # incomplete or unreadable attempt. Sweep it all so its stale
        # acks can't poison the fresh run's commit barriers.
        purge_newer_than(self.directory, None)
        return None

    # -- observability -------------------------------------------------
    def status(self) -> dict:
        """The /status `checkpoint` view (docs/metrics.md)."""
        with self._cond:
            pending = self._pending.step if self._pending else None
        return {
            "directory": self.directory,
            "interval_steps": self.interval_steps,
            "keep": self.keep,
            "commit_count": self._commit_count,
            "last_committed_step": self._last_committed_step,
            "last_write_step": self._last_write_step,
            "pending_step": pending,
            "last_error": self._last_error,
        }


# ---------------------------------------------------------------------------
# Process-wide current manager (the /status hook; set by the elastic
# run loop, which owns the lifecycle).

_current: Optional[CheckpointManager] = None


def set_current(mgr: Optional[CheckpointManager]):
    global _current
    _current = mgr


def current() -> Optional[CheckpointManager]:
    return _current


def manager_from_env(rank: Optional[int] = None,
                     size: Optional[int] = None) -> Optional[CheckpointManager]:
    """Construct the manager the environment asks for, or None when
    ``HOROVOD_CHECKPOINT_DIR`` is unset (the durability plane is
    default-off). Rides the rendezvous KV for acks when the launcher
    configured one."""
    root = env_cfg.checkpoint_dir()
    if not root:
        return None
    if rank is None:
        rank = env_cfg.get_int(env_cfg.RANK, 0)
    if size is None:
        size = env_cfg.get_int(env_cfg.SIZE, 1)
    rdv = None
    addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR)
    port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
    if addr and port:
        from ..backend.rendezvous import RendezvousClient

        rdv = RendezvousClient(addr, port)
    return CheckpointManager(root, rank=rank, size=size, rendezvous=rdv)
