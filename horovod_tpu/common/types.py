"""Core value types shared across the framework.

TPU-native re-design of the reference's C++ core types:
  - Status / StatusType   (ref: horovod/common/common.h Status)
  - DataType              (ref: horovod/common/message.h DataType enum)
  - TensorShape           (ref: horovod/common/common.h TensorShape)
  - ReduceOp constants    (ref: horovod/common/basics.py:210-233)

Unlike the reference (C++ structs shared across an ABI), these are plain
Python dataclasses: the hot data path on TPU is jit-compiled XLA, so the
host-side types only carry metadata for negotiation/validation, never
tensor payloads.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np


class StatusType(enum.IntEnum):
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclass
class Status:
    """Operation status, mirroring the reference Status semantics
    (ref: horovod/common/common.h:126-166)."""

    type: StatusType = StatusType.OK
    reason: str = ""

    def ok(self) -> bool:
        return self.type == StatusType.OK

    def in_progress(self) -> bool:
        return self.type == StatusType.IN_PROGRESS

    @staticmethod
    def OK() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def UnknownError(msg: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, msg)

    @staticmethod
    def PreconditionError(msg: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg)

    @staticmethod
    def Aborted(msg: str) -> "Status":
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def InvalidArgument(msg: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, msg)

    @staticmethod
    def InProgress() -> "Status":
        return Status(StatusType.IN_PROGRESS)


class DataType(enum.IntEnum):
    """Wire dtype enum (ref: horovod/common/wire/message.fbs DataType)."""

    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10  # TPU-native addition: bf16 is the TPU's native reduced type


_NP_TO_DTYPE = {
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.uint16): DataType.UINT16,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.float32): DataType.FLOAT32,
    np.dtype(np.float64): DataType.FLOAT64,
    np.dtype(np.bool_): DataType.BOOL,
}

_DTYPE_TO_NP = {v: k for k, v in _NP_TO_DTYPE.items()}


def to_wire_dtype(dtype) -> DataType:
    """Map a numpy/jax dtype to the wire enum."""
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    if name == "bfloat16":
        return DataType.BFLOAT16
    return _NP_TO_DTYPE[np.dtype(dtype)]


def from_wire_dtype(dt: DataType):
    if dt == DataType.BFLOAT16:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return _DTYPE_TO_NP[DataType(dt)]


def dtype_size(dt: DataType) -> int:
    if dt in (DataType.UINT8, DataType.INT8, DataType.BOOL):
        return 1
    if dt in (DataType.UINT16, DataType.INT16, DataType.FLOAT16, DataType.BFLOAT16):
        return 2
    if dt in (DataType.INT32, DataType.FLOAT32):
        return 4
    return 8


@dataclass(frozen=True)
class TensorShape:
    """Shape metadata (ref: horovod/common/common.h TensorShape)."""

    dims: Tuple[int, ...] = ()

    @staticmethod
    def of(x) -> "TensorShape":
        return TensorShape(tuple(int(d) for d in x.shape))

    def num_elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def to_string(self) -> str:
        return "[" + ", ".join(str(d) for d in self.dims) + "]"


class ReduceOp(enum.IntEnum):
    """Reduction ops exposed to users (ref: horovod/common/basics.py:210-233
    Average/Sum/Adasum constants; Min/Max/Product are TPU-native additions
    that map directly onto lax.pmin/pmax/product psum variants)."""

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Module-level aliases matching horovod's public names.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
