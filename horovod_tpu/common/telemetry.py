"""Process-local metrics registry: Counter / Gauge / Histogram.

The reference exposes almost nothing about a running job beyond the
chrome-trace timeline (ref: horovod/common/timeline.{h,cc}); its only
numeric feedback loop is the autotuner's private bytes/sec score
(ref: parameter_manager.cc). This module is the missing counters layer:
every subsystem (engine cycle loop, tensor queue, response cache, TCP
transport, stall inspector, elastic reset path, autotuner) registers
metrics here, and `metrics_export` renders them as Prometheus text, JSON
dumps, or the `hvd.metrics()` snapshot dict.

Design constraints:

* **Hot path**: instrumentation sites hold direct references to metric
  objects (no per-call registry lookup); an increment is one dict/attr
  access plus an int add under a per-metric lock (uncontended lock
  acquisition under the GIL is ~100ns — negligible next to the engine's
  multi-millisecond cycle sleep).
* **Histograms** use fixed log2 buckets: `observe()` computes the bucket
  index with one `math.frexp` — no bisection, no allocation.
* **Per-engine registries**: each `Engine` may own a registry (the
  in-process multi-rank test harness gives each "rank" its own); real
  one-process-per-rank jobs use the process-wide default registry, which
  module-level sites (retry loops, fault injection) always use.

Cross-rank view: each rank periodically piggybacks a scalar snapshot on
the coordinator control plane (engine/controller.py); rank 0 folds the
blobs into a `FleetView` whose per-metric min/max/sum — tagged with the
extremal rank — makes a straggler show up as a rank-tagged outlier.
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Union

# Default histogram bucket range: 2^-20 s (~1 us) .. 2^6 s (64 s) for
# latencies; byte-sized histograms override with wider exponents.
DEFAULT_MIN_EXP = -20
DEFAULT_MAX_EXP = 6

LabelDict = Optional[Dict[str, str]]


def _metric_key(name: str, labels: LabelDict) -> str:
    """Stable registry key; doubles as the snapshot dict key."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter. `inc()` is the whole API of the hot path."""

    kind = "counter"

    __slots__ = ("name", "labels", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = "", labels: LabelDict = None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: Union[int, float] = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> Union[int, float]:
        return self._value

    def reset(self):
        with self._lock:
            self._value = 0

    def snapshot(self):
        return self._value


class Gauge:
    """Point-in-time value: `set()` for push-style, `set_function()` for
    pull-style (sampled at snapshot time — e.g. queue depth)."""

    kind = "gauge"

    __slots__ = ("name", "labels", "help", "_value", "_fn", "_lock")

    def __init__(self, name: str, help: str = "", labels: LabelDict = None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self.help = help
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()

    def set(self, v: Union[int, float]):
        with self._lock:
            self._value = v

    def inc(self, n: Union[int, float] = 1):
        with self._lock:
            self._value += n

    def dec(self, n: Union[int, float] = 1):
        self.inc(-n)

    def set_function(self, fn: Optional[Callable[[], float]]):
        self._fn = fn

    def clear_function(self, fn: Optional[Callable[[], float]] = None):
        """Detach the pull callback — the public teardown contract for
        owners going away (a dead Engine/backend must not be pinned by
        the process-default registry, nor report frozen state as live).
        Pass the callback you registered to detach only if you are
        still the current owner (a sibling may have taken the gauge
        over); None detaches unconditionally."""
        if fn is None or self._fn == fn:
            self._fn = None

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._value

    def reset(self):
        with self._lock:
            self._value = 0.0

    def snapshot(self):
        return self.value


class Histogram:
    """Fixed log2-bucket histogram.

    Bucket i counts observations in (2^(min_exp+i-1), 2^(min_exp+i)];
    bucket 0 additionally absorbs everything <= 2^min_exp, and a final
    overflow bucket (+Inf) takes v > 2^max_exp. `observe()` is one
    frexp + two int adds + one float add.
    """

    kind = "histogram"

    __slots__ = ("name", "labels", "help", "min_exp", "max_exp",
                 "bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, help: str = "", labels: LabelDict = None,
                 min_exp: int = DEFAULT_MIN_EXP, max_exp: int = DEFAULT_MAX_EXP):
        if max_exp <= min_exp:
            raise ValueError("max_exp must exceed min_exp")
        self.name = name
        self.labels = dict(labels) if labels else None
        self.help = help
        self.min_exp = min_exp
        self.max_exp = max_exp
        # Upper (le) bounds of the finite buckets; +Inf is implicit last.
        self.bounds: List[float] = [
            float(2.0 ** e) for e in range(min_exp, max_exp + 1)
        ]
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def _index(self, v: float) -> int:
        if v <= self.bounds[0]:
            return 0
        if v > self.bounds[-1]:
            return len(self.bounds)
        m, e = math.frexp(v)  # v = m * 2^e, 0.5 <= m < 1
        if m == 0.5:
            e -= 1  # exact powers of two land in their own le bucket
        return e - self.min_exp

    def observe(self, v: Union[int, float]):
        i = self._index(v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "bounds": list(self.bounds),
                "counts": list(self._counts),
            }


Metric = Union[Counter, Gauge, Histogram]


class MetricsRegistry:
    """Thread-safe get-or-create registry of metrics.

    `snapshot()` returns a plain dict (counters/gauges as numbers,
    histograms as {count,sum,bounds,counts}) — the payload behind
    `hvd.metrics()`, the JSON dump and the Prometheus renderer.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, labels: LabelDict,
                       **kwargs) -> Metric:
        key = _metric_key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help=help, labels=labels, **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {key!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "",
                labels: LabelDict = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: LabelDict = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: LabelDict = None,
                  min_exp: int = DEFAULT_MIN_EXP,
                  max_exp: int = DEFAULT_MAX_EXP) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   min_exp=min_exp, max_exp=max_exp)

    def get(self, name: str, labels: LabelDict = None) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(_metric_key(name, labels))

    def metrics(self) -> List[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, Union[int, float, dict]]:
        return {
            _metric_key(m.name, m.labels): m.snapshot()
            for m in self.metrics()
        }

    def scalars(self) -> Dict[str, float]:
        """Flat numeric view for the cross-rank wire blob: counters and
        gauges verbatim; histograms contribute `<name>_count` and
        `<name>_sum` (the fleet aggregates need no buckets)."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            key = _metric_key(m.name, m.labels)
            if isinstance(m, Histogram):
                out[f"{key}_count"] = m.count
                out[f"{key}_sum"] = m.sum
            else:
                v = m.snapshot()
                if isinstance(v, (int, float)) and not math.isnan(v):
                    out[key] = v
        return out

    def reset(self):
        for m in self.metrics():
            m.reset()


# ---------------------------------------------------------------------------
# Process-wide default registry. One-process-per-rank jobs (the real
# deployment shape) put everything here; the threaded multi-rank test
# harness builds per-Engine registries instead.
_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


def counter(name: str, help: str = "", labels: LabelDict = None) -> Counter:
    return _default_registry.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: LabelDict = None) -> Gauge:
    return _default_registry.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: LabelDict = None,
              min_exp: int = DEFAULT_MIN_EXP,
              max_exp: int = DEFAULT_MAX_EXP) -> Histogram:
    return _default_registry.histogram(name, help, labels,
                                       min_exp=min_exp, max_exp=max_exp)


# ---------------------------------------------------------------------------
# Build identity + uptime (standard practice for any scraped process;
# the perf regression reporter stamps the same dict into its JSON so
# every BENCH round is attributable to a build).

_PROCESS_START_MONO = time.monotonic()


def build_info() -> Dict[str, str]:
    """Static build identity: package version + jax version."""
    from ..version import __version__

    try:
        import jax

        jax_version = jax.__version__
    except Exception:  # pragma: no cover - jax is baked into the image
        jax_version = "none"
    return {"version": __version__, "jax": jax_version}


def register_build_info(registry: Optional[MetricsRegistry] = None) -> dict:
    """Register the `horovod_build_info{version=,jax=}` info-gauge
    (constant 1 — the labels ARE the payload, the Prometheus info-metric
    idiom) and `horovod_uptime_seconds` on `registry`. Idempotent."""
    registry = registry or default_registry()
    info = build_info()
    registry.gauge(
        "horovod_build_info",
        "Build identity; the constant-1 value carries its labels",
        labels=info,
    ).set(1)
    registry.gauge(
        "horovod_uptime_seconds",
        "Seconds since this process imported the telemetry layer",
    ).set_function(lambda: time.monotonic() - _PROCESS_START_MONO)
    return info


# ---------------------------------------------------------------------------
# Cross-rank aggregation (coordinator side).

def encode_push(registry: MetricsRegistry, rank: int,
                extra: Optional[dict] = None) -> bytes:
    """Scalar snapshot blob a rank piggybacks on its RequestList.
    `extra` merges additional top-level keys into the JSON — the
    tracing plane rides its span batches ("spans" + "anchor") here so
    trace collection reuses the gather the metrics sync already pays
    for (common/tracing.py TraceCollector)."""
    doc = {"rank": rank, "time": time.time(), "metrics": registry.scalars()}
    if extra:
        doc.update(extra)
    return json.dumps(doc, separators=(",", ":")).encode("utf-8")


class FleetView:
    """Rank 0's per-rank latest scalar snapshots + min/max/sum rollup.

    A straggler is visible directly: `aggregate[metric]["min_rank"]` /
    `["max_rank"]` name the extremal rank for every metric (e.g. the rank
    with the lowest `allreduce_bytes_total` or the deepest
    `tensor_queue_depth`).
    """

    def __init__(self, size: int):
        self.size = size
        self._lock = threading.Lock()
        # rank -> (wall time of snapshot, scalars)
        self._ranks: Dict[int, Tuple[float, Dict[str, float]]] = {}

    def ingest(self, blob: bytes, rank_hint: Optional[int] = None):
        try:
            d = json.loads(blob.decode("utf-8"))
            if not isinstance(d, dict):
                return
            rank = int(d.get("rank", rank_hint if rank_hint is not None else -1))
            scalars = d.get("metrics", {})
            if not isinstance(scalars, dict):
                return
            t = float(d.get("time", time.time()))
        except Exception:
            return  # a malformed blob must never take down the cycle loop
        if rank < 0:
            return
        with self._lock:
            self._ranks[rank] = (t, scalars)

    def ranks(self) -> Dict[int, Dict[str, float]]:
        with self._lock:
            return {r: dict(s) for r, (_, s) in self._ranks.items()}

    def snapshot(self) -> dict:
        now = time.time()
        with self._lock:
            per_rank = {
                r: {"age_seconds": max(now - t, 0.0), "metrics": dict(s)}
                for r, (t, s) in self._ranks.items()
            }
        agg: Dict[str, dict] = {}
        for r, entry in per_rank.items():
            for name, v in entry["metrics"].items():
                a = agg.get(name)
                if a is None:
                    agg[name] = {"min": v, "max": v, "sum": v, "count": 1,
                                 "min_rank": r, "max_rank": r}
                else:
                    if v < a["min"]:
                        a["min"], a["min_rank"] = v, r
                    if v > a["max"]:
                        a["max"], a["max_rank"] = v, r
                    a["sum"] += v
                    a["count"] += 1
        return {"size": self.size, "ranks": per_rank, "aggregate": agg}


# ---------------------------------------------------------------------------
# One-line step summaries (MetricsCallback in callbacks.py / keras).

class StepSummary:
    """Computes deltas between calls: step time, allreduce MB/s, response
    cache hit rate, plus the goodput plane's window view — goodput% of
    the window's wall-clock and exposed-comm ms per batch
    (docs/goodput.md). Shared by the JAX-loop and Keras
    MetricsCallbacks."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or default_registry()
        self._t0 = time.monotonic()
        # Seed baselines from the live counters: the first window must
        # not absorb pre-training traffic (initial parameter broadcast,
        # cold-start negotiation misses).
        (self._bytes0, self._hits0, self._misses0, self._exposed0,
         self._stall0) = self._read()

    def _read(self) -> Tuple[float, float, float, float, float]:
        s = self.registry.scalars()
        return (
            s.get("horovod_allreduce_bytes_total", 0.0),
            s.get("horovod_response_cache_hits_total", 0.0),
            s.get("horovod_response_cache_misses_total", 0.0),
            s.get("horovod_exposed_comm_seconds_total", 0.0),
            s.get("horovod_ckpt_stall_seconds_total", 0.0),
        )

    def line(self, steps: int) -> str:
        """Summary line covering the `steps` batches since the last call."""
        now = time.monotonic()
        b, h, m, ex, stall = self._read()
        dt = max(now - self._t0, 1e-9)
        db = b - self._bytes0
        dh, dm = h - self._hits0, m - self._misses0
        dex = max(ex - self._exposed0, 0.0)
        dstall = max(stall - self._stall0, 0.0)
        (self._t0, self._bytes0, self._hits0, self._misses0,
         self._exposed0, self._stall0) = now, b, h, m, ex, stall
        step_ms = dt / max(steps, 1) * 1e3
        mbps = db / dt / 1e6
        lookups = dh + dm
        hit_pct = (100.0 * dh / lookups) if lookups else 0.0
        # Window goodput%: the share of this window's wall-clock NOT
        # lost to exposed comm or checkpoint stalls (the in-window form
        # of the ledger's job-level ratio).
        good_pct = 100.0 * max(dt - dex - dstall, 0.0) / dt
        comm_ms = dex / max(steps, 1) * 1e3
        return (f"step {step_ms:.1f}ms | allreduce {mbps:.1f}MB/s | "
                f"cache hit {hit_pct:.0f}% | goodput {good_pct:.0f}% | "
                f"comm {comm_ms:.1f}ms")


class StepSummaryLogger:
    """Interval gate + rank-0 filter around StepSummary — the shared body
    of the JAX-loop and Keras MetricsCallbacks (which differ only in
    their framework base class)."""

    def __init__(self, interval: int = 100, log_fn=None,
                 root_only: bool = True,
                 registry: Optional[MetricsRegistry] = None):
        if interval <= 0:
            raise ValueError("interval must be positive")
        if log_fn is None:
            from ..utils.logging import get_logger

            log_fn = lambda line: get_logger().info("%s", line)  # noqa: E731
        self.interval = interval
        self.root_only = root_only
        self._log = log_fn
        self._summary = StepSummary(registry)
        self._batches = 0

    def step(self):
        """Call once per batch; logs every `interval` batches (rank 0
        only when root_only)."""
        from . import basics

        self._batches += 1
        if self._batches % self.interval:
            return
        if self.root_only and basics.is_initialized() and basics.rank() != 0:
            return
        self._log(f"[metrics] {self._summary.line(self.interval)}")
