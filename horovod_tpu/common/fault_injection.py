"""Deterministic fault-injection harness for the CPU data plane.

The elastic contract (exceptions.py:4-9) is only as good as the failure
modes that exercise it. This module makes chaos a first-class, testable
input: the transports call the hooks below at every connect/send/recv,
and a training loop (or the chaos smoke script) advances a step counter
— so "sever rank 2's link to rank 0 after 3 frames" or "kill rank 1 at
step 5" is a deterministic scenario, not a flaky race.

Two configuration surfaces, mirroring the reference's env-first style
(HOROVOD_* knobs; common.h:64-90):

* env var ``HOROVOD_FAULT_INJECT`` — a ';'-separated rule list parsed at
  first use, e.g.::

      HOROVOD_FAULT_INJECT="kill:step=5"
      HOROVOD_FAULT_INJECT="sever:peer=0:after=3;delay:peer=2:secs=0.2"

* the programmatic API — ``install(rules)`` / ``add_rule(...)`` /
  ``clear()`` for unit tests.

Rule actions:

``kill``    ``os._exit(1)`` when the step counter reaches ``step=N``
            (``advance_step()`` is the trigger point — the worker's
            training loop calls it once per batch).
``sever``   raise + hard-close the connection on the Nth I/O with
            ``peer=P`` (``after=K`` frames, default 0 = immediately).
``drop``    silently swallow sends to ``peer=P`` (the peer then hangs
            until its recv timeout — exercises bounded-time detection).
``delay``   sleep ``secs=S`` before I/O with ``peer=P``.
``wedge``   at ``step=N`` the process freezes without dying: the
            training thread parks forever inside ``advance_step``,
            every subsequent transport I/O parks forever inside its
            injector hook, and the heartbeat monitor (common/health.py
            checks ``injector.wedged``) stops beating — while the
            process stays alive and its sockets stay open, so the
            kernel keeps ACKing and no FIN ever arrives. The closest
            analogue of a live-locked / GC-frozen / NFS-stuck worker,
            and the scenario only heartbeat detection can bound.
``hang``    the matching I/O (``peer=P``, ``after=K``, ``op=...``)
            parks forever — a single stuck network operation, with the
            rest of the process (heartbeats included) still running.
``preempt`` deliver the preemption notice (``HOROVOD_PREEMPT_SIGNAL``,
            default SIGTERM — via ``os.kill`` to self, so the real
            signal handler runs) at ``step=N`` or after ``secs=T``
            wall seconds. The process keeps running: the drain plane
            (common/drain.py) owns what happens next — checkpoint-now
            at the next commit, stamp handoff, clean exit. The
            announced-preemption analogue of ``kill``, drivable from
            tests and scripts/preemption_smoke.py without a spot fleet.
``diskfail``raise ``OSError`` on the Nth matching disk I/O (checkpoint
            shard writes, metrics dumps — everything routed through
            ``utils/atomic_file.py``). Optional ``path=SUBSTR`` confines
            it to paths containing the substring, ``op=read|write`` to
            one direction (default: both), ``after=K`` to skip the
            first K matches. The disk-full / yanked-NFS scenario the
            durability plane (docs/checkpoint.md) must absorb without
            ever committing a manifest referencing a missing shard.
``diskslow``sleep ``secs=S`` before the matching disk I/O — a slow
            (gcsfuse-cold, contended) store; checkpoint writes must
            stay off the training thread and absorb this as latency,
            not failure.

Every rule may carry ``rank=R`` so one job-wide env var can target a
single rank, and ``op=connect|send|recv`` (network rules) or
``op=read|write`` (disk rules) to confine it to one hook (default:
send+recv for sever/drop/delay; read+write for disk rules).

The harness is a no-op singleton when no rules are installed — the
hooks cost one attribute check on the hot path.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

ENV_VAR = "HOROVOD_FAULT_INJECT"


def _fault_counter(action: str):
    from . import telemetry

    return telemetry.counter(
        "horovod_faults_injected_total",
        "Faults fired by the chaos harness, by action",
        labels={"action": action},
    )

# Hook verdicts (sever is raised, not returned)
PASS = "pass"
DROP = "drop"


class InjectedFault(ConnectionError):
    """Raised by a sever rule; transports translate it like any other
    transport failure (→ TransportError → elastic recovery)."""


class InjectedDiskFault(OSError):
    """Raised by a diskfail rule; an OSError subclass so disk writers
    exercise exactly their real-disk-error paths (retry, skip, count)."""


_NET_ACTIONS = ("kill", "sever", "drop", "delay", "wedge", "hang",
                "preempt")
_DISK_ACTIONS = ("diskfail", "diskslow")
# Serving-plane actions hook the front-door admission path instead of
# transport I/O (docs/serving.md "Failure drills"). killdoor kills the
# CURRENT active door after `after=N` accepted requests — the
# front-door analogue of `kill`, drivable mid-traffic so the failover
# election (serving/doors.py) is exercised deterministically.
_SERVING_ACTIONS = ("killdoor",)


@dataclass
class Rule:
    action: str                       # kill | sever | drop | delay | wedge |
                                      #   hang | diskfail | diskslow
    peer: Optional[int] = None        # None = any peer
    rank: Optional[int] = None        # None = any rank
    op: Optional[str] = None          # net: connect|send|recv; disk:
                                      #   read|write; None = default set
    after: int = 0                    # fire from the Nth matching I/O on
    step: Optional[int] = None        # kill trigger
    secs: float = 0.0                 # delay duration
    path: Optional[str] = None        # disk rules: path substring match
    # mutable state: matching-I/O counter per rule
    hits: int = field(default=0, compare=False)


def parse_spec(spec: str) -> List[Rule]:
    """Parse the ``HOROVOD_FAULT_INJECT`` rule grammar."""
    rules: List[Rule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        action = fields[0].strip().lower()
        if action not in _NET_ACTIONS + _DISK_ACTIONS + _SERVING_ACTIONS:
            raise ValueError(f"unknown fault action {action!r} in {part!r}")
        kw: Dict[str, str] = {}
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(f"bad fault field {f!r} in {part!r}")
            k, v = f.split("=", 1)
            kw[k.strip()] = v.strip()
        rule = Rule(action=action)
        if "peer" in kw:
            rule.peer = int(kw["peer"])
        if "rank" in kw:
            rule.rank = int(kw["rank"])
        if "path" in kw:
            if action not in _DISK_ACTIONS:
                raise ValueError(
                    f"path= applies to disk rules only (got {part!r})")
            rule.path = kw["path"]
        if "op" in kw:
            if action in _SERVING_ACTIONS:
                raise ValueError(
                    f"op= does not apply to {action} rules (got {part!r})")
            valid = (("read", "write") if action in _DISK_ACTIONS
                     else ("connect", "send", "recv"))
            if kw["op"] not in valid:
                raise ValueError(
                    f"bad fault op {kw['op']!r} for {action} "
                    f"(expected one of {valid})")
            rule.op = kw["op"]
        if action == "drop" and kw.get("op") not in (None, "send"):
            # A recv cannot be "dropped" — the bytes either arrive or
            # they don't. Reject instead of silently arming a no-op.
            raise ValueError(
                f"drop rules apply to sends only (got op={kw['op']!r})"
            )
        if "after" in kw:
            rule.after = int(kw["after"])
        if "step" in kw:
            rule.step = int(kw["step"])
        if "secs" in kw:
            rule.secs = float(kw["secs"])
        if rule.action in ("kill", "wedge") and rule.step is None:
            raise ValueError(f"{rule.action} rule needs step=N: {part!r}")
        if rule.action == "preempt" and rule.step is None and rule.secs <= 0:
            raise ValueError(
                f"preempt rule needs step=N or secs=T: {part!r}")
        if rule.action in ("delay", "diskslow") and rule.secs <= 0:
            raise ValueError(f"{rule.action} rule needs secs=S: {part!r}")
        if rule.action == "killdoor" and rule.after < 0:
            raise ValueError(f"killdoor needs after=N >= 0: {part!r}")
        rules.append(rule)
    return rules


class FaultInjector:
    """Process-wide injector; see module docstring for the rule model."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[Rule] = []
        self._step = 0
        self._env_loaded = False
        # Fast-path flag: hooks bail on a single read when inactive.
        self.active = False
        # Set when a wedge rule fires: the process is frozen-but-alive.
        # Threads that consult it (I/O hooks, the heartbeat monitor)
        # park on the event, which is never set free again for the
        # process's lifetime — exactly a wedge.
        self._wedge_fired = threading.Event()
        # Wall-clock preempt triggers (secs= rules) ride daemon timers.
        self._timers: List[threading.Timer] = []

    @property
    def wedged(self) -> bool:
        return self._wedge_fired.is_set()

    @staticmethod
    def _park_forever():  # pragma: no cover - by construction never returns
        threading.Event().wait()

    # -- configuration -------------------------------------------------
    def _load_env(self):
        if self._env_loaded:
            return
        self._env_loaded = True
        spec = os.environ.get(ENV_VAR, "")
        if spec:
            self._rules.extend(parse_spec(spec))
            self.active = True
            logger.warning("fault injection armed: %s", spec)
            self._arm_preempt_timers()

    def install(self, rules: List[Rule]):
        with self._lock:
            self._env_loaded = True  # explicit install overrides env
            self._cancel_timers()
            self._rules = list(rules)
            self._step = 0
            self.active = bool(self._rules)
            self._arm_preempt_timers()

    def add_rule(self, rule: Rule):
        with self._lock:
            self._env_loaded = True
            self._rules.append(rule)
            self.active = True
            self._arm_preempt_timers()

    def clear(self):
        with self._lock:
            self._cancel_timers()
            self._rules = []
            self._step = 0
            self._env_loaded = True
            self.active = False
            # Future I/O proceeds again; threads already parked by a
            # fired wedge stay parked (each holds its own private event
            # — a wedge is forever for the threads it caught).
            self._wedge_fired = threading.Event()

    def reload_env(self):
        """Re-read HOROVOD_FAULT_INJECT (tests mutate the env)."""
        with self._lock:
            self._cancel_timers()
            self._rules = []
            self._step = 0
            self._env_loaded = False
            self._load_env()
            self.active = bool(self._rules)

    # -- preempt (announced-preemption) triggers -----------------------
    def _arm_preempt_timers(self):
        """Arm wall-clock ``preempt:secs=T`` triggers (lock held).
        Step-triggered preempt rules fire from advance_step instead.
        ``hits`` doubles as the armed/fired-once marker."""
        own_rank = env_cfg.get_int(env_cfg.RANK, -1)
        for r in self._rules:
            if r.action != "preempt" or r.step is not None or r.hits:
                continue
            if r.rank is not None and r.rank != own_rank:
                continue
            r.hits = 1
            t = threading.Timer(r.secs, self._fire_preempt,
                                args=(f"after {r.secs:.1f}s",))
            t.daemon = True
            t.name = "hvd-fault-preempt"
            self._timers.append(t)
            t.start()

    def _cancel_timers(self):
        for t in self._timers:
            t.cancel()
        self._timers = []

    @staticmethod
    def _fire_preempt(what: str):
        """Deliver the notice through the REAL signal path (os.kill to
        self), so the drain plane's installed handler — not a shortcut —
        does the work, exactly as a platform-delivered notice would."""
        logger.error("fault injection: preemption notice (%s)", what)
        _fault_counter("preempt").inc()
        os.kill(os.getpid(), env_cfg.preempt_signal())

    # -- triggers --------------------------------------------------------
    def advance_step(self) -> int:
        """Advance the worker step counter; fires any armed kill rule.
        Called by training loops (and the chaos smoke worker) once per
        batch so worker death is deterministic in *steps*, not seconds."""
        if not self.active:
            return 0
        wedge = False
        preempt = False
        with self._lock:
            self._load_env()
            self._step += 1
            step = self._step
            own_rank = env_cfg.get_int(env_cfg.RANK, -1)
            for r in self._rules:
                if r.step is None:
                    continue
                # rank= targeting works here too: the job-wide env var
                # contract (module docstring) — only the named rank's
                # process dies/wedges, everyone else keeps stepping.
                if r.rank is not None and r.rank != own_rank:
                    continue
                if r.action == "kill" and step >= r.step:
                    logger.error("fault injection: killing worker at step %d",
                                 step)
                    # os._exit: no atexit/finally — the closest analogue
                    # of a SIGKILLed or OOM-killed worker that still lets
                    # the OS send FIN on its sockets.
                    os._exit(1)
                if r.action == "wedge" and step >= r.step \
                        and not self._wedge_fired.is_set():
                    logger.error("fault injection: wedging worker at step %d "
                                 "(alive, sockets open, heartbeats stop)",
                                 step)
                    _fault_counter("wedge").inc()
                    self._wedge_fired.set()
                    wedge = True
                if r.action == "preempt" and step >= r.step and not r.hits:
                    r.hits = 1
                    preempt = True
        if preempt:
            # Deliver OUTSIDE the lock: the drain handler runs at the
            # next bytecode boundary of this (main) thread and must
            # never find the injector lock held.
            self._fire_preempt(f"at step {step}")
        if wedge or self._wedge_fired.is_set():
            # Park OUTSIDE the lock (other threads must still reach
            # their own hooks to park themselves).
            self._park_forever()
        return step

    @property
    def step(self) -> int:
        return self._step

    def check_io(self, rank: int, peer: int, op: str) -> str:
        """Hook for a transport about to do `op` ('connect'|'send'|'recv')
        with `peer`. Returns PASS or DROP; raises InjectedFault for a
        sever (the caller hard-closes the connection and translates)."""
        if not self.active:
            return PASS
        if self._wedge_fired.is_set():
            # A wedged process's I/O never completes and never errors:
            # the socket stays open, the bytes just stop.
            self._park_forever()
        hang = False
        with self._lock:
            self._load_env()
            verdict = PASS
            for r in self._rules:
                if r.action in ("kill", "wedge", "preempt") \
                        or r.action in _DISK_ACTIONS \
                        or r.action in _SERVING_ACTIONS:
                    continue
                if r.rank is not None and r.rank != rank:
                    continue
                if r.peer is not None and r.peer != peer:
                    continue
                if r.op is not None:
                    if r.op != op:
                        continue
                elif op == "connect":
                    # sever/drop/delay default to data-plane I/O only
                    continue
                elif r.action == "drop" and op != "send":
                    # Drop is send-only; a recv must not advance its hit
                    # counter either, or `after=K` would fire early.
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.action == "delay":
                    # Sleep outside the lock? Delay rules are test-only
                    # and short; holding the lock keeps ordering exact.
                    _fault_counter("delay").inc()
                    time.sleep(r.secs)
                elif r.action == "drop":
                    _fault_counter("drop").inc()
                    verdict = DROP
                elif r.action == "sever":
                    _fault_counter("sever").inc()
                    raise InjectedFault(
                        f"fault injection severed rank {rank} <-> peer "
                        f"{peer} ({op})"
                    )
                elif r.action == "hang":
                    _fault_counter("hang").inc()
                    hang = True
        if hang:
            # Park outside the lock: only the MATCHING I/O freezes;
            # everything else (heartbeats included) keeps flowing.
            logger.error("fault injection: hanging rank %d %s with peer %d",
                         rank, op, peer)
            self._park_forever()
        return verdict

    def check_door_admit(self, active: bool):
        """Hook the serving frontend calls once per ACCEPTED request
        (after the admission-queue offer succeeded). ``active`` says
        whether this process is currently the ACTIVE front door — a
        killdoor rule only counts (and only kills) the active door, so
        standby-door traffic never trips it. ``after=N`` means N
        requests are accepted and land; the N+1th admission brings the
        door down mid-flight, exactly the failover drill
        (scripts/serving_smoke.py phase 4)."""
        if not self.active or not active:
            return
        with self._lock:
            self._load_env()
            own_rank = env_cfg.get_int(env_cfg.RANK, -1)
            for r in self._rules:
                if r.action != "killdoor":
                    continue
                if r.rank is not None and r.rank != own_rank:
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                logger.error(
                    "fault injection: killing front door after %d "
                    "accepted requests", r.after)
                _fault_counter("killdoor").inc()
                # os._exit like `kill`: sockets reset, no cleanup — the
                # survivors' liveness verdict does the rest.
                os._exit(1)

    def check_disk(self, op: str, path: str):
        """Hook for a disk writer/reader about to do `op`
        ('read'|'write') on `path` (utils/atomic_file.py calls this on
        every atomic write and checked read). diskslow sleeps; diskfail
        raises InjectedDiskFault — an OSError, exactly what a real disk
        error looks like to the caller."""
        if not self.active:
            return
        if self._wedge_fired.is_set():
            self._park_forever()
        own_rank = env_cfg.get_int(env_cfg.RANK, -1)
        sleep_s = 0.0
        with self._lock:
            self._load_env()
            for r in self._rules:
                if r.action not in _DISK_ACTIONS:
                    continue
                if r.rank is not None and r.rank != own_rank:
                    continue
                if r.op is not None and r.op != op:
                    continue
                if r.path is not None and r.path not in path:
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.action == "diskslow":
                    _fault_counter("diskslow").inc()
                    sleep_s += r.secs
                else:
                    _fault_counter("diskfail").inc()
                    raise InjectedDiskFault(
                        f"fault injection failed disk {op} of {path!r}")
        # Sleep OUTSIDE the lock: disk I/O runs on background writer
        # threads, and a slow-disk injection that held the shared lock
        # would stall every network check_io hook — heartbeats
        # included — turning a disk fault into false dead-peer
        # declarations. (Network `delay` deliberately sleeps under the
        # lock: it fires on the very I/O being delayed.)
        if sleep_s > 0:
            time.sleep(sleep_s)


# The process-wide singleton the transports consult.
injector = FaultInjector()


def get_injector() -> FaultInjector:
    with injector._lock:
        injector._load_env()
    return injector


def advance_step() -> int:
    """Module-level convenience for training loops: one call per batch."""
    return get_injector().advance_step()
