"""Declarative SLO/anomaly alert engine over the on-box time-series
(docs/health.md).

Nothing in the stack *watches* the signals the earlier planes surface:
a serving p99 breach, a persistent straggler, a stalled checkpoint all
sit in `/metrics` waiting for a human to scrape them. This module
closes the loop: a rule engine evaluated on every sampler tick
(common/timeseries.py), Google-SRE-shaped rule types, and latched
firing→resolved state with duration hysteresis so a single noisy
sample never pages.

Rule types:

* ``threshold`` — a gauge (last value, family max) or counter rate
  above/below a bound for ≥ ``for_seconds``;
* ``burn_rate`` — a windowed histogram quantile vs an SLO target in a
  fast AND a slow window (the multi-window burn-rate pattern: the fast
  window reacts, the slow window filters blips);
* ``regression`` — a windowed statistic vs the median of trailing
  adjacent windows, relative tolerance ("this got worse", no absolute
  bound needed);
* ``straggler`` — the same rank named by an attribution gauge in ≥ K
  of the last N samples (every verdict in this stack names a rank;
  alerts do too);
* ``overdue`` — a progress counter that stopped advancing for longer
  than ``factor`` × its own observed median cadence (self-calibrating
  "checkpoint overdue").

State machine per rule: a breach must hold ``for_seconds`` before the
alert latches FIRING (counted in ``horovod_alerts_total{rule=,
state="fire"}``, an ``alert.fire`` instant in the flight recorder, a
log line); it must then stay clear ``clear_seconds`` before RESOLVED.
Stale data — the sampler ring's newest sample older than the staleness
bound — never fires anything: no data is not evidence of breach.

Fleet view: each rank's firing set rides the telemetry piggyback
(controller → ``FleetAlerts`` on rank 0), so the coordinator's
``/alerts`` names the offending rank job-wide — the same path every
PR 5 liveness verdict takes.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import env as env_cfg
from ..utils.logging import get_logger
from . import timeseries as ts

logger = get_logger()

# evaluate() verdict: (breach, value, detail) — None = not enough data
# (a rule with nothing to say must stay silent, never fire).
Verdict = Optional[Tuple[bool, float, dict]]


class Rule:
    """Base rule: name, doc, hysteresis bounds, override plumbing."""

    kind = "rule"

    def __init__(self, name: str, description: str = "",
                 for_seconds: float = 0.0,
                 clear_seconds: Optional[float] = None,
                 enabled: bool = True):
        self.name = name
        self.description = description
        self.for_seconds = for_seconds
        self.clear_seconds = (for_seconds if clear_seconds is None
                              else clear_seconds)
        self.enabled = enabled
        # Parameters the user explicitly pinned via HOROVOD_ALERT_RULES;
        # live re-wiring (serving/_wire_alert_rules) must not clobber
        # them — an explicit override always wins over a derived value.
        self._overridden: set = set()

    def evaluate(self, store: ts.TimeSeriesStore,
                 now: Optional[float] = None) -> Verdict:
        raise NotImplementedError

    def set_param(self, key: str, value: str):
        """HOROVOD_ALERT_RULES override: coerce to the attribute's
        current type so `serving_p99_slo:target_s=0.05` just works.
        Unknown keys are loud — a typo'd override that silently does
        nothing is worse than an error."""
        if not hasattr(self, key) or key in ("name", "kind"):
            raise ValueError(f"rule {self.name!r} has no parameter {key!r}")
        cur = getattr(self, key)
        if isinstance(cur, bool):
            value = value.lower() not in ("0", "false", "no", "off")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            value = int(value)
        elif isinstance(cur, float) or cur is None:
            value = float(value)
        setattr(self, key, value)
        self._overridden.add(key)

    def config(self) -> dict:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}


class ThresholdRule(Rule):
    """Gauge / rate / family-max vs a bound for >= for_seconds."""

    kind = "threshold"

    def __init__(self, name: str, metric: str, threshold: float,
                 op: str = "above", mode: str = "last",
                 window_s: float = 60.0, **kw):
        super().__init__(name, **kw)
        self.metric = metric
        self.threshold = threshold
        self.op = op          # above | below
        self.mode = mode      # last | rate | family_max
        self.window_s = window_s

    def evaluate(self, store, now=None) -> Verdict:
        detail: dict = {}
        if self.mode == "rate":
            value = store.rate(self.metric, self.window_s)
        elif self.mode == "family_max":
            latest = store.latest()
            if latest is None:
                return None
            items = [(k, v) for k, v in
                     ts.family_items(latest, self.metric).items()
                     if isinstance(v, (int, float)) and v == v]
            if not items:
                return None
            key, value = max(items, key=lambda kv: kv[1])
            detail["series"] = key
        else:
            latest = store.latest()
            value = latest.get(self.metric) if latest else None
            if not isinstance(value, (int, float)) or value != value:
                return None
        if value is None:
            return None
        breach = (value > self.threshold if self.op == "above"
                  else value < self.threshold)
        detail["threshold"] = self.threshold
        return breach, float(value), detail


class BurnRateRule(Rule):
    """Windowed histogram quantile vs an SLO target, fast + slow
    window both breaching (multi-window burn rate). target_s <= 0
    disarms (the serving SLO default until the knob is set)."""

    kind = "burn_rate"

    def __init__(self, name: str, metric: str, target_s: float,
                 quantile: float = 0.99, fast_window_s: float = 60.0,
                 slow_window_s: float = 300.0, min_count: int = 10, **kw):
        super().__init__(name, **kw)
        self.metric = metric
        self.target_s = target_s
        self.quantile = quantile
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.min_count = min_count

    def evaluate(self, store, now=None) -> Verdict:
        if self.target_s <= 0:
            return None
        fast_w = store.hist_window(self.metric, self.fast_window_s, now)
        if fast_w is None or fast_w["count"] < self.min_count:
            return None
        fast = ts.quantile_from_counts(
            fast_w["bounds"], fast_w["counts"], self.quantile)
        slow = store.quantile(self.metric, self.quantile,
                              self.slow_window_s, now)
        if fast is None or slow is None:
            return None
        breach = fast > self.target_s and slow > self.target_s
        return breach, fast, {
            "target_s": self.target_s,
            "fast_q": round(fast, 6), "slow_q": round(slow, 6),
            "quantile": self.quantile,
        }


class RegressionRule(Rule):
    """Windowed quantile vs the median of trailing adjacent windows:
    fires when "now" is worse than "recently" by more than the relative
    tolerance. Needs >= min_baselines trailing windows with data, so a
    cold start never fires."""

    kind = "regression"

    def __init__(self, name: str, metric: str, window_s: float = 60.0,
                 baselines: int = 5, min_baselines: int = 2,
                 tolerance: float = 0.75, quantile: float = 0.5,
                 min_count: int = 20, **kw):
        super().__init__(name, **kw)
        self.metric = metric
        self.window_s = window_s
        self.baselines = baselines
        self.min_baselines = min_baselines
        self.tolerance = tolerance
        self.quantile = quantile
        self.min_count = min_count

    def evaluate(self, store, now=None) -> Verdict:
        samples = store.samples()
        if not samples:
            return None
        now = samples[-1][1] if now is None else now
        cur_w = ts.histogram_window(samples, self.metric,
                                    self.window_s, now)
        if cur_w is None or cur_w["count"] < self.min_count:
            return None
        cur = ts.quantile_from_counts(
            cur_w["bounds"], cur_w["counts"], self.quantile)
        if cur is None:
            return None
        base_vals = []
        for i in range(1, self.baselines + 1):
            v = ts.window_quantile(samples, self.metric, self.quantile,
                                   self.window_s,
                                   now=now - i * self.window_s)
            if v is not None:
                base_vals.append(v)
        if len(base_vals) < self.min_baselines:
            return None
        base_vals.sort()
        baseline = base_vals[len(base_vals) // 2]
        if baseline <= 0:
            return None
        ratio = cur / baseline
        return ratio > 1.0 + self.tolerance, cur, {
            "baseline": round(baseline, 6),
            "ratio": round(ratio, 3),
            "tolerance": self.tolerance,
        }


class StragglerRule(Rule):
    """The attribution gauge named the same rank in >= k of the last n
    samples. The detail carries the rank — the alert IS the verdict.

    Two guards keep a healthy mesh quiet: `activity_metric` must have
    advanced across the window (an idle mesh freezes the gauge on the
    last straggler, which is history, not evidence), and the default
    k/n demands 90% dominance sustained for `for_seconds` — on a
    balanced mesh the last-to-arrive rank is a coin flip, and a coin
    must not page."""

    kind = "straggler"

    def __init__(self, name: str,
                 metric: str = "horovod_straggler_rank",
                 k: int = 9, n: int = 10,
                 activity_metric: str = "horovod_responses_total", **kw):
        kw.setdefault("for_seconds", 30.0)
        kw.setdefault("clear_seconds", 0.0)
        super().__init__(name, **kw)
        self.metric = metric
        self.k = k
        self.n = n
        self.activity_metric = activity_metric

    def evaluate(self, store, now=None) -> Verdict:
        samples = store.samples()
        if len(samples) < self.n:
            return None
        window = samples[-self.n:]
        if self.activity_metric:
            first = window[0][2].get(self.activity_metric)
            last = window[-1][2].get(self.activity_metric)
            if (not isinstance(first, (int, float))
                    or not isinstance(last, (int, float))
                    or last == first):
                return None  # no negotiations: the gauge is stale history
        vals = [s[2].get(self.metric) for s in window]
        vals = [int(v) for v in vals
                if isinstance(v, (int, float)) and v == v and v >= 0]
        if not vals:
            return False, -1.0, {}
        counts: Dict[int, int] = {}
        for v in vals:
            counts[v] = counts.get(v, 0) + 1
        rank, hits = max(counts.items(), key=lambda kv: kv[1])
        return hits >= self.k, float(rank), {
            "rank": rank, "hits": hits, "of": self.n,
        }


class OverdueRule(Rule):
    """A progress counter stopped advancing for > factor x its own
    observed median cadence. Self-calibrating: needs >= 2 observed
    advances (one interval) before it can fire, so a job that never
    checkpoints never pages about checkpoints."""

    kind = "overdue"

    def __init__(self, name: str, metric: str, factor: float = 2.0,
                 min_advances: int = 2, **kw):
        super().__init__(name, **kw)
        self.metric = metric
        self.factor = factor
        self.min_advances = min_advances

    def evaluate(self, store, now=None) -> Verdict:
        samples = store.samples()
        if not samples:
            return None
        now = samples[-1][1] if now is None else now
        advances: List[float] = []  # mono times the counter moved
        prev = None
        for _, mono, snap in samples:
            v = snap.get(self.metric)
            if not isinstance(v, (int, float)):
                continue
            if prev is not None and v > prev:
                advances.append(mono)
            prev = v
        if prev is None or len(advances) < self.min_advances:
            return None
        gaps = sorted(b - a for a, b in zip(advances, advances[1:]))
        median_gap = gaps[len(gaps) // 2]
        age = now - advances[-1]
        limit = self.factor * median_gap
        return age > limit, age, {
            "overdue_seconds": round(age, 3),
            "median_interval_seconds": round(median_gap, 3),
            "factor": self.factor,
        }


# ---------------------------------------------------------------------------
# Default rule set

def default_rules() -> List[Rule]:
    """The built-in rules, wired to real signals. Rules over planes
    that are off in this process simply never see data and stay
    silent; the serving SLO rule additionally disarms itself while
    HOROVOD_SERVING_SLO_P99_MS is 0."""
    hb_interval = env_cfg.heartbeat_interval_seconds()
    hb_limit = env_cfg.heartbeat_miss_limit()
    rules: List[Rule] = [
        BurnRateRule(
            "serving_p99_slo", "horovod_serving_request_seconds",
            target_s=env_cfg.serving_slo_p99_ms() / 1e3,
            description="Serving p99 latency above the "
                        "HOROVOD_SERVING_SLO_P99_MS target in both the "
                        "fast and slow windows"),
        RegressionRule(
            "cycle_time_regression", "horovod_cycle_seconds",
            description="Engine cycle time p50 regressed vs the "
                        "trailing-window baseline (the step got slower)"),
        StragglerRule(
            "persistent_straggler",
            description="horovod_straggler_rank named the same rank in "
                        ">= k of the last n samples — one rank is "
                        "holding every collective back"),
        ThresholdRule(
            "heartbeat_stale", "horovod_heartbeat_age_seconds",
            threshold=0.8 * hb_interval * max(hb_limit, 1),
            mode="family_max",
            enabled=env_cfg.heartbeat_enabled(),
            description="A peer's heartbeat age is approaching the "
                        "dead-declaration bound (silence, not yet a "
                        "verdict)"),
        ThresholdRule(
            "admission_queue_saturated", "horovod_serving_queue_depth",
            threshold=0.9 * env_cfg.serving_queue_depth(),
            for_seconds=20.0,
            description="Serving admission queue >= 90% of "
                        "HOROVOD_SERVING_QUEUE_DEPTH — 429 backpressure "
                        "is imminent or already happening"),
        OverdueRule(
            "checkpoint_overdue", "horovod_checkpoint_commits_total",
            description="No checkpoint commit within 2x the observed "
                        "commit cadence — durability is stalled"),
        # Goodput plane (docs/goodput.md): the job-level efficiency
        # number operators gate on. The ratio gauge is NaN until the
        # first completed step, so a job that never demarcates steps
        # stays silent here.
        ThresholdRule(
            "goodput_degraded", "horovod_goodput_ratio",
            threshold=0.5, op="below", mode="last", for_seconds=120.0,
            description="Goodput ratio (productive step compute / job "
                        "wall-clock) held below the threshold — badput "
                        "is eating the fleet; /goodput attributes it"),
        RegressionRule(
            "exposed_comm_regression", "horovod_exposed_comm_step_seconds",
            description="Per-step exposed (training-thread-blocking) "
                        "communication p50 regressed vs the trailing-"
                        "window baseline — overlap got worse or a link "
                        "got slower"),
    ]
    return rules


def apply_rules_spec(spec: str, rules: List[Rule]) -> List[Rule]:
    """Apply the HOROVOD_ALERT_RULES token list (utils/env.py) to a
    rule set: `none`/`off` disables everything, `-name` disables one,
    `name` (re-)enables one, `name:param=value:...` overrides
    parameters. Unknown rule names and bad parameters raise — a typo'd
    alert config must fail loudly at startup, not page never."""
    by_name = {r.name: r for r in rules}
    for token in (t.strip() for t in spec.split(",")):
        if not token:
            continue
        if token.lower() in ("none", "off"):
            for r in rules:
                r.enabled = False
            continue
        disable = token.startswith("-")
        fields = token.lstrip("-").split(":")
        name = fields[0]
        rule = by_name.get(name)
        if rule is None:
            raise ValueError(
                f"unknown alert rule {name!r} in {env_cfg.ALERT_RULES} "
                f"(have: {', '.join(sorted(by_name))})")
        rule.enabled = not disable
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(f"bad alert override {f!r} in {token!r}")
            k, v = f.split("=", 1)
            rule.set_param(k.strip(), v.strip())
    return rules


# ---------------------------------------------------------------------------
# The engine

class _AlertState:
    __slots__ = ("firing", "since_wall", "breach_start", "clear_start",
                 "value", "detail", "fires", "resolves")

    def __init__(self):
        self.firing = False
        self.since_wall: Optional[float] = None
        self.breach_start: Optional[float] = None
        self.clear_start: Optional[float] = None
        self.value: Optional[float] = None
        self.detail: dict = {}
        self.fires = 0
        self.resolves = 0


class AlertEngine:
    """Evaluates the rule set on each sampler tick and latches per-rule
    firing state. `stale_after` bounds trust in the ring: when the
    newest sample is older than it, evaluation is skipped entirely
    (state frozen, noted in status) — stale data never fires OR
    resolves anything."""

    def __init__(self, store: ts.TimeSeriesStore, registry,
                 rules: Optional[List[Rule]] = None, tracer=None,
                 stale_after: Optional[float] = None,
                 rules_spec: Optional[str] = None):
        self.store = store
        self.registry = registry
        self.tracer = tracer
        if rules is None:
            rules = default_rules()
        if rules_spec is None:
            rules_spec = env_cfg.alert_rules_spec()
        if rules_spec:
            apply_rules_spec(rules_spec, rules)
        self.rules = rules
        if stale_after is None:
            stale_after = 3 * max(env_cfg.metrics_sample_seconds(), 1.0)
        self.stale_after = stale_after
        self._state: Dict[str, _AlertState] = {
            r.name: _AlertState() for r in rules}
        self._lock = threading.Lock()
        self.stale = False
        self._m_firing = registry.gauge(
            "horovod_alerts_firing", "Alert rules currently latched firing")
        self._m_total: Dict[Tuple[str, str], object] = {}

    def _count(self, rule: str, state: str):
        key = (rule, state)
        c = self._m_total.get(key)
        if c is None:
            c = self._m_total[key] = self.registry.counter(
                "horovod_alerts_total",
                "Alert transitions by rule and state",
                labels={"rule": rule, "state": state})
        c.inc()

    def _instant(self, name: str, rule: Rule, st: _AlertState):
        if self.tracer is not None and getattr(self.tracer, "enabled", False):
            self.tracer.instant(name, cat="alert", args=dict(
                {"rule": rule.name, "value": st.value}, **st.detail))

    # -- the tick ------------------------------------------------------
    def evaluate(self, store: Optional[ts.TimeSeriesStore] = None,
                 now: Optional[float] = None):
        """Run every enabled rule once. Registered as a sampler tick
        callback; callable directly in tests with a synthetic store."""
        store = store if store is not None else self.store
        now = time.monotonic() if now is None else now
        age = store.last_age()
        self.stale = age < 0 or age > self.stale_after
        if self.stale:
            return
        with self._lock:
            for rule in self.rules:
                if not rule.enabled:
                    continue
                try:
                    verdict = rule.evaluate(store, now)
                except Exception:
                    logger.exception("alert rule %s failed", rule.name)
                    continue
                st = self._state[rule.name]
                if verdict is None:
                    # No data: clear any pending breach window (a gap
                    # must not bridge two short breaches into one long
                    # one), keep latched state as-is.
                    st.breach_start = None
                    continue
                breach, st.value, st.detail = verdict
                if breach:
                    st.clear_start = None
                    if st.breach_start is None:
                        st.breach_start = now
                    if (not st.firing
                            and now - st.breach_start >= rule.for_seconds):
                        st.firing = True
                        st.since_wall = time.time()
                        st.fires += 1
                        self._count(rule.name, "fire")
                        self._instant("alert.fire", rule, st)
                        from . import events as events_mod

                        events_mod.emit(
                            events_mod.ALERT_FIRE,
                            severity=events_mod.WARN,
                            rule=rule.name, value=st.value)
                        logger.warning(
                            "ALERT FIRING %s: value=%s %s", rule.name,
                            st.value, st.detail)
                else:
                    st.breach_start = None
                    if st.firing:
                        if st.clear_start is None:
                            st.clear_start = now
                        if now - st.clear_start >= rule.clear_seconds:
                            st.firing = False
                            st.resolves += 1
                            st.clear_start = None
                            self._count(rule.name, "resolve")
                            self._instant("alert.resolve", rule, st)
                            from . import events as events_mod

                            events_mod.emit(events_mod.ALERT_CLEAR,
                                            rule=rule.name)
                            logger.info("alert resolved: %s", rule.name)
            self._m_firing.set(
                sum(1 for s in self._state.values() if s.firing))

    # -- views ---------------------------------------------------------
    def firing(self) -> List[dict]:
        with self._lock:
            return [
                {"rule": name, "value": st.value, "detail": dict(st.detail),
                 "since": st.since_wall}
                for name, st in sorted(self._state.items()) if st.firing
            ]

    def status(self) -> dict:
        with self._lock:
            rules = {}
            for rule in self.rules:
                st = self._state[rule.name]
                rules[rule.name] = {
                    "kind": rule.kind,
                    "enabled": rule.enabled,
                    "firing": st.firing,
                    "since": st.since_wall,
                    "value": st.value,
                    "detail": dict(st.detail),
                    "fires": st.fires,
                    "resolves": st.resolves,
                    "description": rule.description,
                }
            return {
                "stale": self.stale,
                "firing": sorted(n for n, s in self._state.items()
                                 if s.firing),
                "rules": rules,
            }

    def push_state(self) -> dict:
        """Compact per-rank state for the telemetry piggyback (the
        coordinator's FleetAlerts ingests it)."""
        return {"firing": self.firing()}


# ---------------------------------------------------------------------------
# Coordinator-side fold

class FleetAlerts:
    """Rank 0's per-rank alert state, folded from the telemetry
    piggyback blobs — the fleet-wide `/alerts` view that names which
    RANK an alert is firing on, not just that one is."""

    def __init__(self, size: int):
        self.size = size
        self._lock = threading.Lock()
        self._ranks: Dict[int, Tuple[float, dict]] = {}

    def ingest_blob(self, rank: int, blob: bytes):
        try:
            d = json.loads(blob.decode("utf-8"))
            alerts = d.get("alerts")
        except Exception:
            return  # malformed blobs never take down the cycle loop
        if isinstance(alerts, dict):
            with self._lock:
                self._ranks[int(rank)] = (time.time(), alerts)

    def snapshot(self) -> dict:
        now = time.time()
        with self._lock:
            per_rank = {
                r: {"age_seconds": round(max(now - t, 0.0), 3),
                    "firing": list(state.get("firing", []))}
                for r, (t, state) in sorted(self._ranks.items())
            }
        by_rule: Dict[str, List[int]] = {}
        for r, entry in per_rank.items():
            for f in entry["firing"]:
                by_rule.setdefault(f.get("rule", "?"), []).append(r)
        return {
            "size": self.size,
            "ranks": per_rank,
            "firing_by_rule": {k: sorted(v) for k, v in
                               sorted(by_rule.items())},
        }
