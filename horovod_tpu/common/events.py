"""Lifecycle event journal — the fifth observability plane
(docs/events.md).

The metrics, tracing, timeseries/alert and goodput planes answer "how
much", "where did the time go", "what is trending wrong" and "how much
became training" — but *lifecycle* truth (re-mesh epochs, drains,
preemptions, checkpoint commits/restores, weight swaps, alert
fire/clear, controller decisions, host quarantines) was scattered
across KV rows, log lines and counters. This module records it as
typed, causally orderable events:

    (seq, wall_ns, mono_ns, rank, epoch, step, severity, kind, attrs)

``seq`` is a per-process monotonically increasing index (the dedup
key, exactly the flight recorder's scheme); ``epoch`` is the elastic
topology epoch the process was meshed into when the event fired;
``step`` is the goodput ledger's global committed-step cursor. Epoch
and step are what make the journal *causally* orderable across ranks:
wall clocks skew, but a `drain.drained` at (epoch 3, step 120) is
unambiguously before the `elastic.remesh` that opened epoch 4.

Three sinks, none on the hot path:

* **Ring** — a bounded in-memory buffer (`EventRecorder`, the
  SpanRecorder design: GIL-atomic append, amortized trim, overwrites
  counted in ``horovod_events_dropped_total``), always available for
  /events, /status and post-mortems.
* **Spool** — with ``HOROVOD_EVENTS_DIR`` set, a writer thread appends
  each event as one JSON line to ``events_rank<r>.jsonl`` (flushed
  every ``HOROVOD_EVENTS_SPOOL_SECONDS``) and atomically writes a
  clock-anchor sidecar via utils/atomic_file. The journal survives the
  process; a torn tail line from a hard kill is tolerated on replay
  (`read_journal`).
* **Fleet fold** — each rank's new events ride the telemetry piggyback
  to rank 0 (engine/controller.py, the same mechanism spans and alert
  state use); `FleetEvents` dedups by (rank, seq), aligns wall clocks
  with the health plane's RTT-estimated offsets, and serves the merged
  causally-ordered chronicle at /events.

``HOROVOD_EVENTS_BUFFER=0`` disables the plane entirely: `emit`
returns before touching a clock, no spool thread, no fold.
"""
from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..utils import atomic_file, clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

# -- severities --------------------------------------------------------
INFO = "info"
WARN = "warn"
ERROR = "error"

# -- event kinds (docs/events.md "Kinds") ------------------------------
# Engine lifecycle (engine/engine.py)
ENGINE_INIT = "engine.init"
ENGINE_SHUTDOWN = "engine.shutdown"
# Native data-plane core (cc/native.py, docs/native.md): which ladder
# rung this rank's engine actually runs — emitted once at engine init.
NATIVE_LOADED = "native.loaded"
NATIVE_FALLBACK = "native.fallback"
# Elastic run loop (elastic/run.py) + driver (runner/elastic/driver.py)
ELASTIC_RESET = "elastic.reset"
ELASTIC_RESTORE = "elastic.restore"
ELASTIC_REMESH = "elastic.remesh"
ELASTIC_JOIN = "elastic.join"
ELASTIC_EVICT = "elastic.evict"
# Graceful drain (common/drain.py)
DRAIN_NOTICE = "drain.notice"
DRAIN_COMMIT = "drain.commit_barrier"
DRAIN_DRAINED = "drain.drained"
DRAIN_PEER = "drain.peer"
# Durability (common/checkpoint.py + goodput replay accounting)
CKPT_COMMIT = "ckpt.commit"
CKPT_RESTORE = "ckpt.restore"
CKPT_REPLAY = "ckpt.replay"
# Alert engine (common/alerts.py)
ALERT_FIRE = "alert.fire"
ALERT_CLEAR = "alert.clear"
# Elasticity controller (runner/elastic/controller.py)
CONTROLLER_DECISION = "controller.decision"
# Serving plane (serving/replicas.py, serving/doors.py,
# serving/autoscaler.py)
SERVING_SWAP_PREPARE = "serving.swap_prepare"
SERVING_SWAP = "serving.swap"
SERVING_EVICT = "serving.evict"
SERVING_DOOR_ELECTED = "serving.door_elected"
SERVING_SCALE = "serving.scale"
# Liveness plane (common/health.py)
HEALTH_VERDICT = "health.verdict"
# Host bookkeeping (runner/elastic/driver.py + discovery)
HOST_QUARANTINE = "host.quarantine"
HOST_BLACKLIST = "host.blacklist"

# Journal filename scheme under HOROVOD_EVENTS_DIR. The driver process
# (no rank) spools as rank -1 -> "events_driver.jsonl".
JOURNAL_PREFIX = "events_rank"
DRIVER_JOURNAL = "events_driver.jsonl"
ANCHOR_SUFFIX = ".anchor.json"

_FIELDS = ("seq", "wall_ns", "mono_ns", "rank", "epoch", "step", "sev",
           "kind", "attrs")


def journal_path(directory: str, rank: int) -> str:
    name = DRIVER_JOURNAL if rank < 0 else f"{JOURNAL_PREFIX}{rank}.jsonl"
    return os.path.join(directory, name)


def to_dict(ev: tuple) -> dict:
    d = dict(zip(_FIELDS, ev))
    if d.get("attrs") is None:
        d.pop("attrs", None)
    return d


# Worker processes learn their epoch from MESH_SCOPE; the driver
# process has no scope env, so the ElasticDriver installs a provider
# for its live epoch — otherwise every driver event would stamp -1 and
# sort before the whole worker chronicle.
_epoch_provider = None


def set_epoch_provider(fn):
    global _epoch_provider
    _epoch_provider = fn


def _current_epoch() -> int:
    """The elastic topology epoch this process is meshed into; -1
    outside elastic mode (static jobs have exactly one 'epoch')."""
    try:
        fn = _epoch_provider
        if fn is not None:
            e = fn()
        else:
            from ..backend import elastic_env

            e = elastic_env._current_epoch()
        return -1 if e is None else int(e)
    except Exception:  # pragma: no cover - defensive
        return -1


def _current_step() -> int:
    """The goodput ledger's global step cursor (0 before any step)."""
    try:
        from . import goodput

        led = goodput.active()
        return int(led.current_step) if led is not None else 0
    except Exception:  # pragma: no cover - defensive
        return 0


class EventRecorder:
    """Bounded ring of lifecycle events + optional JSONL spool.

    The ring is the SpanRecorder design (common/tracing.py): `record`
    is a GIL-atomic `list.append` with the seq drawn from an
    `itertools.count`; the bound is enforced by an amortized trim once
    the list doubles past capacity, and overwrites are counted in
    ``horovod_events_dropped_total`` — losing the start of an incident
    must never read as "nothing happened".
    """

    def __init__(self, capacity: Optional[int] = None, registry=None,
                 rank: Optional[int] = None,
                 spool_dir: Optional[str] = None,
                 spool_seconds: Optional[float] = None):
        from . import telemetry

        if capacity is None:
            capacity = env_cfg.events_buffer()
        self.capacity = max(int(capacity), 0)
        self.rank = (env_cfg.get_int(env_cfg.RANK, 0)
                     if rank is None else rank)
        self._buf: List[tuple] = []
        self._seq = itertools.count()
        self._trim_at = 2 * self.capacity
        self._lock = threading.Lock()
        self._m_dropped = None
        self._m_recorded = None
        if self.capacity:
            registry = (telemetry.default_registry()
                        if registry is None else registry)
            self._m_dropped = registry.counter(
                "horovod_events_dropped_total",
                "Lifecycle events lost before reaching an output (ring "
                "overwrites, spool queue drops)")
            self._m_recorded = registry.counter(
                "horovod_events_recorded_total",
                "Lifecycle events recorded by the events plane")
        # -- spool (HOROVOD_EVENTS_DIR) --------------------------------
        self._spool_q: Optional[queue.Queue] = None
        self._spool_thread: Optional[threading.Thread] = None
        self._spool_stop = threading.Event()
        self._spool_kick = threading.Event()
        self._spool_path: Optional[str] = None
        self._spool_seconds = (env_cfg.events_spool_seconds()
                               if spool_seconds is None else spool_seconds)
        if spool_dir is None:
            spool_dir = env_cfg.events_dir()
        if self.capacity and spool_dir:
            self._start_spool(spool_dir)

    # -- recording (the only call sites emitters touch) ----------------
    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(self, kind: str, severity: str = INFO,
               attrs: Optional[dict] = None,
               rank: Optional[int] = None) -> Optional[tuple]:
        if not self.capacity:
            return None
        mono = clock.mono_ns()
        ev = (next(self._seq), clock.mono_to_wall_ns(mono), mono,
              self.rank if rank is None else rank,
              _current_epoch(), _current_step(), severity, kind,
              dict(attrs) if attrs else None)
        buf = self._buf
        buf.append(ev)
        if self._m_recorded is not None:
            self._m_recorded.inc()
        if len(buf) >= self._trim_at:
            self._trim()
        q = self._spool_q
        if q is not None:
            try:
                q.put_nowait(ev)
            except queue.Full:
                if self._m_dropped is not None:
                    self._m_dropped.inc()
        return ev

    def _trim(self):
        with self._lock:
            excess = len(self._buf) - self.capacity
            if excess > 0:
                del self._buf[:excess]
                if self._m_dropped is not None:
                    self._m_dropped.inc(excess)

    def _total(self) -> int:
        buf = self._buf
        return buf[-1][0] + 1 if buf else 0

    def depth(self) -> int:
        return min(len(self._buf), self.capacity)

    @property
    def dropped(self) -> int:
        """Events no longer retained by the ring (exact)."""
        return max(self._total() - self.depth(), 0)

    def snapshot(self) -> List[tuple]:
        with self._lock:
            evs = list(self._buf)
        evs.sort(key=lambda e: e[0])
        return evs[-self.capacity:]

    def batch_since(self, cursor: int, limit: int = 1024
                    ) -> Tuple[List[tuple], int]:
        """Events with seq >= cursor (oldest `limit`) and the next
        cursor — the piggyback's incremental read (tracing idiom)."""
        evs = [e for e in self.snapshot() if e[0] >= cursor]
        if len(evs) > limit:
            evs = evs[:limit]
        nxt = evs[-1][0] + 1 if evs else self._total()
        return evs, nxt

    def tail(self, n: int = 8) -> List[dict]:
        """The newest n events, dict form — the /status compact tail."""
        return [to_dict(e) for e in self.snapshot()[-n:]]

    def status(self) -> dict:
        st = {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "depth": self.depth(),
            "dropped": self.dropped,
        }
        if self._spool_path:
            st["spool"] = {"path": self._spool_path,
                           "flush_seconds": self._spool_seconds}
        return st

    # -- spool ---------------------------------------------------------
    def _start_spool(self, directory: str):
        """Arm the JSONL journal writer: events are queued here and
        appended+flushed by a daemon thread (the timeline.py pattern —
        the recording path never touches a file). The clock-anchor
        sidecar is written atomically (utils/atomic_file) so readers
        can align this journal's wall clock against other ranks'."""
        try:
            path = journal_path(directory, self.rank)
            atomic_file.atomic_write_text(
                path + ANCHOR_SUFFIX,
                json.dumps({"rank": self.rank, **clock.anchor_meta()}),
                make_dirs=True)
        except OSError as e:
            logger.warning("events spool disabled: %s", e)
            return
        self._spool_path = path
        self._spool_q = queue.Queue(maxsize=max(self.capacity, 1024))
        self._spool_thread = threading.Thread(
            target=self._spool_loop, name="hvd-events-spool", daemon=True)
        self._spool_thread.start()
        # The writer is a daemon thread: without this, a clean exit
        # (including the SystemExit a drain raises) could kill it with
        # the final events — the interesting ones — still queued.
        import atexit

        atexit.register(self.flush_spool)

    def _spool_loop(self):
        assert self._spool_q is not None and self._spool_path is not None
        try:
            f = open(self._spool_path, "a", encoding="utf-8")
        except OSError as e:  # pragma: no cover - dir vanished
            logger.warning("events spool open failed: %s", e)
            self._spool_q = None
            return
        with f:
            while True:
                self._spool_kick.wait(self._spool_seconds)
                self._spool_kick.clear()
                stopped = self._spool_stop.is_set()
                wrote = False
                while True:
                    try:
                        ev = self._spool_q.get_nowait()
                    except queue.Empty:
                        break
                    f.write(json.dumps(to_dict(ev),
                                       separators=(",", ":")) + "\n")
                    wrote = True
                if wrote:
                    f.flush()
                if stopped:
                    return

    def flush_spool(self, timeout: float = 2.0):
        """Kick the writer thread and wait (bounded) until everything
        queued so far is on disk — engine shutdown calls this so the
        journal's tail covers the shutdown events themselves."""
        t, q = self._spool_thread, self._spool_q
        if t is None or q is None or not t.is_alive():
            return
        self._spool_kick.set()
        deadline = time.monotonic() + timeout
        while not q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)
        # one more tick so the write+flush after the final get lands
        time.sleep(0.05)

    def close_spool(self, timeout: float = 5.0):
        """Drain and stop the journal writer (engine shutdown)."""
        t = self._spool_thread
        if t is None:
            return
        self._spool_stop.set()
        self._spool_kick.set()
        t.join(timeout=timeout)
        self._spool_thread = None

    # -- piggyback push (engine/controller.py wires this) --------------
    def make_push(self):
        """A zero-arg callable for the telemetry piggyback: each call
        returns {"batch": [...new events...], "anchor": {...}} or None
        when nothing is new. Cursor state lives in the closure — one
        pusher per engine, exactly like the tracer's span cursor."""
        state = {"cursor": 0}

        def push() -> Optional[dict]:
            evs, state["cursor"] = self.batch_since(state["cursor"])
            if not evs:
                return None
            return {"batch": [list(e) for e in evs],
                    "anchor": clock.anchor_meta()}

        return push


def causal_order(evs: List[dict]) -> List[dict]:
    """Sort event dicts (each carrying epoch/step/adj_wall_ns/rank/seq)
    into the chronicle order: epoch, step cursor, skew-adjusted wall,
    rank, seq. Events from processes with no step cursor (step <= 0:
    the driver, a worker before its first step) *inherit* the step of
    the last stepped event at their wall position within the epoch —
    otherwise every control-plane event (evict, quarantine, controller
    decision) would sort to the front of its epoch instead of
    interleaving where it happened. Deterministic: a pure function of
    the event set, independent of ingestion order."""
    def _wall(d):
        return d.get("adj_wall_ns", d.get("wall_ns", 0))

    pre = sorted(evs, key=lambda d: (d.get("epoch", -1), _wall(d),
                                     d.get("rank", 0), d.get("seq", 0)))
    eff: Dict[int, int] = {}
    cur_epoch: Optional[int] = None
    cursor = 0
    for d in pre:
        e = d.get("epoch", -1)
        if e != cur_epoch:
            cur_epoch, cursor = e, 0
        s = d.get("step", 0) or 0
        if s > 0:
            cursor = max(cursor, s)
            eff[id(d)] = s
        else:
            eff[id(d)] = cursor
    pre.sort(key=lambda d: (d.get("epoch", -1), eff[id(d)], _wall(d),
                            d.get("rank", 0), d.get("seq", 0)))
    return pre


class FleetEvents:
    """Rank 0's fold of every rank's event batches (the piggyback
    sink), merged into one causally-ordered chronicle.

    Ordering: (epoch, step, skew-adjusted wall_ns, rank, seq) — epochs
    are collectively agreed, the step cursor is collective at commit
    boundaries, and only *within* one (epoch, step) cell does the
    ordering fall back to wall clocks, where the skew adjustment (the
    health plane's RTT-estimated offsets, wall anchors as fallback)
    bounds the error to ~rtt/2. The same total order on the same event
    set regardless of ingestion order — determinism is what makes two
    operators reading the same chronicle see the same incident."""

    def __init__(self, size: int, capacity: int = 4096):
        self.size = size
        self.capacity = capacity
        self._lock = threading.Lock()
        self._by_rank: Dict[int, deque] = {}
        self._seen: Dict[int, int] = {}  # rank -> next unseen seq
        self._anchors: Dict[int, dict] = {}
        # peer mono-clock offsets (peer - local), from health heartbeats
        self._mono_offsets: Dict[int, int] = {}
        self._local_anchor = clock.anchor_meta()

    def set_offsets(self, offsets: Dict[int, int]):
        """Best-effort mono-clock offsets from the heartbeat monitor
        (tracing.estimate_offset samples, minimum-RTT wins)."""
        with self._lock:
            self._mono_offsets.update(offsets)

    def ingest(self, rank: int, batch: List[list],
               anchor: Optional[dict] = None):
        with self._lock:
            if anchor:
                self._anchors[rank] = anchor
            dq = self._by_rank.get(rank)
            if dq is None:
                dq = self._by_rank[rank] = deque(maxlen=self.capacity)
            nxt = self._seen.get(rank, 0)
            for ev in batch:
                ev = tuple(ev)
                if ev[0] < nxt:
                    continue  # re-pushed batch (dedup by seq)
                nxt = ev[0] + 1
                dq.append(ev)
            self._seen[rank] = nxt

    def ingest_blob(self, peer_rank: int, blob: Optional[bytes]):
        """Feed from a telemetry piggyback blob; tolerant of blobs
        without an events section (mixed-version fleets)."""
        if not blob:
            return
        try:
            d = json.loads(blob.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return
        sec = d.get("events")
        if not isinstance(sec, dict):
            return
        batch = sec.get("batch")
        if isinstance(batch, list):
            self.ingest(peer_rank, batch, anchor=sec.get("anchor"))

    # -- clock alignment ------------------------------------------------
    def skew_ns(self, rank: int) -> int:
        """Estimated wall-clock skew of `rank` relative to this
        process: remote_wall - local_wall at the same instant. The
        health plane's mono offset is exact up to rtt/2 when present;
        wall anchors reduce to 0 when both processes trust the same
        wall clock (single host, NTP-synced fleet)."""
        with self._lock:
            anchor = self._anchors.get(rank)
            mono_off = self._mono_offsets.get(rank)
        if anchor is None:
            return 0
        try:
            remote_w2m = (int(anchor["wall_anchor_ns"])
                          - int(anchor["mono_anchor_ns"]))
            local_w2m = (int(self._local_anchor["wall_anchor_ns"])
                         - int(self._local_anchor["mono_anchor_ns"]))
        except (KeyError, TypeError, ValueError):
            return 0
        if mono_off is None:
            # Without an RTT sample, both walls are trusted: skew 0.
            return 0
        # remote_wall = remote_mono + remote_w2m; at the same instant
        # remote_mono = local_mono + mono_off, so:
        return mono_off + remote_w2m - local_w2m

    def skews(self) -> Dict[int, int]:
        with self._lock:
            ranks = list(self._by_rank)
        return {r: self.skew_ns(r) for r in ranks}

    # -- merged chronicle -----------------------------------------------
    def merged(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            rows = [(r, ev) for r, dq in self._by_rank.items()
                    for ev in dq]
        skews = {r: self.skew_ns(r) for r in {r for r, _ in rows}}
        out = []
        for r, ev in rows:
            d = to_dict(ev)
            d["adj_wall_ns"] = ev[1] - skews.get(r, 0)
            out.append(d)
        out = causal_order(out)
        if limit is not None:
            out = out[-limit:]
        return out

    def snapshot(self) -> dict:
        with self._lock:
            ranks = sorted(self._by_rank)
            depths = {str(r): len(self._by_rank[r]) for r in ranks}
        return {
            "ranks": ranks,
            "depths": depths,
            "skew_ns": {str(r): self.skew_ns(r) for r in ranks},
            "events": self.merged(),
        }


# ---------------------------------------------------------------------------
# Journal replay (incident_report.py + tests)

def read_journal(path: str) -> List[dict]:
    """Parse one JSONL journal, tolerating the torn tail line a hard
    kill leaves behind (the spool appends; only a complete line is a
    complete event). Unparseable interior lines are skipped too — one
    corrupt line must not cost the chronicle."""
    out: List[dict] = []
    try:
        data = atomic_file.checked_read_bytes(path)
    except (OSError, IOError):
        return out
    for line in data.decode("utf-8", "replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue  # torn/corrupt line
        if isinstance(d, dict) and "kind" in d:
            out.append(d)
    return out


def read_anchor(journal: str) -> Optional[dict]:
    try:
        data = atomic_file.checked_read_bytes(journal + ANCHOR_SUFFIX)
        d = json.loads(data.decode("utf-8"))
        return d if isinstance(d, dict) else None
    except (OSError, IOError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Process-wide recorder (the emit() singleton; engines and the driver
# share it — lifecycle truth is per-process, not per-engine).

_current: Optional[EventRecorder] = None
_current_lock = threading.Lock()


def current(rank: Optional[int] = None) -> EventRecorder:
    global _current
    with _current_lock:
        if _current is None:
            _current = EventRecorder(rank=rank)
        return _current


def set_current(rec: Optional[EventRecorder]):
    global _current
    with _current_lock:
        if _current is not None and _current is not rec:
            _current.close_spool(timeout=1.0)
        _current = rec


def active() -> Optional[EventRecorder]:
    return _current


def local_view() -> dict:
    """The single-rank /events body — mesh mode has no engine (and so
    no fleet fold); its exporters serve this rank's ring alone, the
    same ``local`` shape engine._events_view produces."""
    rec = active()
    if rec is None or not rec.enabled:
        return {"local": {"enabled": False}}
    return {"local": {**rec.status(), "events": rec.tail(n=rec.capacity)}}


def set_rank(rank: int):
    """Elastic renumbering: later events carry the live rank (the
    journal file keeps its original name — events self-describe)."""
    rec = _current
    if rec is not None:
        rec.rank = rank


def emit(kind: str, severity: str = INFO, rank: Optional[int] = None,
         **attrs) -> Optional[tuple]:
    """The one-line emitter every subsystem calls. Zero cost when the
    plane is disabled (capacity 0): one attribute read + one branch."""
    rec = _current
    if rec is None:
        rec = current()
    if not rec.capacity:
        return None
    return rec.record(kind, severity=severity, attrs=attrs or None,
                      rank=rank)
