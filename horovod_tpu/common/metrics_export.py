"""Metrics export: Prometheus text exposition, JSON dumps, live endpoints.

Three consumers of the `telemetry` registry (docs/metrics.md):

* `hvd.metrics()` — in-process snapshot dict (common/basics.py).
* `HOROVOD_METRICS_FILE=<path>` — a daemon thread dumps a JSON snapshot
  every `HOROVOD_METRICS_FILE_INTERVAL` seconds (atomic tmp+rename, like
  spark/store.py's crash-safe write). `{rank}` in the path expands to the
  rank so multi-process runs don't clobber one file.
* `HOROVOD_METRICS_PORT=<port>` — rank 0 serves Prometheus text at
  `/metrics`, a JSON snapshot at `/metrics.json`, and live per-rank state
  at `/status` (pending tensors, queue depth, last-cycle age — the live
  version of the stall inspector's post-mortem) from a daemon thread.

Everything here is default-off: with neither env var set, no thread is
started and no socket is opened (the registry itself costs a few int
adds per engine cycle).
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..utils import atomic_file
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from . import telemetry

logger = get_logger()


# ---------------------------------------------------------------------------
# Renderers

def _prom_name(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and newline (exposition spec §text
    format details) — a multi-line help string would otherwise corrupt
    every line after it for strict parsers."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value: str) -> str:
    """Label values escape backslash, double-quote and newline. A
    version label like `0.4.37+cuda"test` must round-trip, not break
    the series line."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels, extra: str = "") -> str:
    parts = []
    if labels:
        parts.extend(f'{k}="{_escape_label_value(labels[k])}"'
                     for k in sorted(labels))
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v) -> str:
    if isinstance(v, float):
        if v != v:  # NaN
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        return repr(v)
    return str(v)


def to_prometheus(registry: Optional[telemetry.MetricsRegistry] = None) -> str:
    """Render the registry in Prometheus text exposition format 0.0.4.
    Histogram buckets are emitted cumulatively with `le` labels plus the
    `+Inf` bucket, `_sum` and `_count`, per the exposition spec."""
    registry = registry or telemetry.default_registry()
    lines = []
    seen_headers = set()
    # Sort by name so all series of one family render contiguously:
    # lazily-created labeled series (op latency) otherwise interleave
    # with other families, which strict exposition parsers reject.
    for m in sorted(registry.metrics(), key=lambda m: m.name):
        name = _prom_name(m.name)
        if name not in seen_headers:
            seen_headers.add(name)
            if m.help:
                lines.append(f"# HELP {name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {name} {m.kind}")
        if isinstance(m, telemetry.Histogram):
            snap = m.snapshot()
            cum = 0
            for bound, c in zip(snap["bounds"], snap["counts"]):
                cum += c
                le = 'le="' + _fmt(bound) + '"'
                lines.append(f"{name}_bucket{_prom_labels(m.labels, le)} {cum}")
            cum += snap["counts"][-1]
            le_inf = 'le="+Inf"'
            lines.append(f"{name}_bucket{_prom_labels(m.labels, le_inf)} {cum}")
            lines.append(f"{name}_sum{_prom_labels(m.labels)} {_fmt(snap['sum'])}")
            lines.append(f"{name}_count{_prom_labels(m.labels)} {snap['count']}")
        else:
            lines.append(f"{name}{_prom_labels(m.labels)} {_fmt(m.snapshot())}")
    return "\n".join(lines) + "\n"


def _unescape_help(s: str) -> str:
    """Inverse of `_escape_help`, single left-to-right pass — chained
    str.replace would corrupt a literal backslash followed by 'n'
    (escaped `\\\\n` must decode to backslash+n, not backslash+LF)."""
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_label_block(s: str) -> dict:
    """Inverse of `_prom_labels`: parse `{k="v",...}` honoring the
    value escapes (backslash, quote, newline)."""
    out = {}
    i = 1  # past '{'
    end = len(s) - 1  # before '}'
    while i < end:
        eq = s.index("=", i)
        name = s[i:eq].strip().lstrip(",").strip()
        if s[eq + 1] != '"':
            raise ValueError(f"unquoted label value in {s!r}")
        k = eq + 2
        val = []
        while True:
            c = s[k]
            if c == "\\":
                nxt = s[k + 1]
                val.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
                k += 2
            elif c == '"':
                k += 1
                break
            else:
                val.append(c)
                k += 1
        out[name] = "".join(val)
        i = k
    return out


def parse_prometheus(text: str):
    """Parse text exposition 0.0.4 back into
    ``(samples, types, helps)``: samples keyed the same way as
    `MetricsRegistry.snapshot()` (``name{k="v",...}`` with sorted
    labels), types/helps keyed by family name. The conformance
    round-trip test — and anything in-repo that scrapes a live
    `/metrics` — consumes this instead of regexing the text."""
    samples: dict = {}
    types: dict = {}
    helps: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            types[name] = kind
            continue
        if line.startswith("# HELP "):
            _, _, name, rest = line.split(None, 3)
            helps[name] = _unescape_help(rest)
            continue
        if line.startswith("#"):
            continue
        brace = line.find("{")
        if brace >= 0:
            close = line.rindex("}")
            name = line[:brace]
            labels = _parse_label_block(line[brace:close + 1])
            value = line[close + 1:].strip()
        else:
            name, value = line.split(None, 1)
            labels = {}
        v = float(value)
        key = name
        if labels:
            inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
            key = f"{name}{{{inner}}}"
        samples[key] = v
    return samples, types, helps


def to_json(registry: Optional[telemetry.MetricsRegistry] = None,
            fleet: Optional[telemetry.FleetView] = None,
            extra: Optional[dict] = None) -> str:
    registry = registry or telemetry.default_registry()
    doc = {"time": time.time(), "metrics": registry.snapshot()}
    if fleet is not None:
        doc["fleet"] = fleet.snapshot()
    if extra:
        doc.update(extra)
    return json.dumps(doc, indent=1, sort_keys=True)


# ---------------------------------------------------------------------------
# Periodic JSON file dump

class MetricsFileWriter:
    """Daemon thread dumping a JSON snapshot every `interval` seconds.
    Writes are atomic (tmp + rename) so a scraper never reads a torn
    file; a final dump runs at stop() so shutdown state is captured."""

    def __init__(self, path: str, registry: Optional[telemetry.MetricsRegistry] = None,
                 fleet: Optional[telemetry.FleetView] = None,
                 interval: float = 30.0, rank: int = 0):
        self.path = path.replace("{rank}", str(rank))
        self.registry = registry or telemetry.default_registry()
        self.fleet = fleet
        self.interval = max(interval, 0.05)
        self.rank = rank
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-metrics-file", daemon=True
        )

    def start(self) -> "MetricsFileWriter":
        self._thread.start()
        return self

    def _dump(self):
        try:
            atomic_file.atomic_write_text(
                self.path,
                to_json(self.registry, self.fleet,
                        extra={"rank": self.rank}))
        except OSError as e:  # an unwritable path must not kill the job
            logger.warning("metrics file dump to %s failed: %s", self.path, e)

    def _loop(self):
        while not self._stop.wait(self.interval):
            self._dump()
        self._dump()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# Live HTTP endpoint (rank 0)

class _Handler(BaseHTTPRequestHandler):
    server_version = "hvd-metrics"

    def _send(self, code: int, body: str, ctype: str):
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802 (http.server API)
        srv: "MetricsHTTPServer" = self.server.owner  # type: ignore[attr-defined]
        try:
            if self.path.startswith("/metrics.json"):
                self._send(200, to_json(srv.registry, srv.fleet),
                           "application/json")
            elif self.path.startswith("/metrics"):
                self._send(200, to_prometheus(srv.registry),
                           "text/plain; version=0.0.4; charset=utf-8")
            else:
                # Registered views (add_view): /<name> serves whatever
                # the provider returns — dicts render as JSON, strings
                # pass through verbatim (pre-rendered documents like the
                # merged Perfetto /trace body).
                name = self.path.lstrip("/").split("?")[0].split("/")[0]
                fn = srv.get_view(name)
                if fn is None:
                    views = ", ".join("/" + v for v in srv.view_names())
                    self._send(404, f"not found: try /metrics, "
                               f"/metrics.json{', ' + views if views else ''}"
                               "\n", "text/plain")
                else:
                    body = fn()
                    if isinstance(body, str):
                        self._send(200, body, "application/json")
                    else:
                        self._send(200, json.dumps(body, indent=1,
                                                   sort_keys=True),
                                   "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-response; nothing left to answer
        except Exception as e:  # a broken provider must not kill the server
            try:
                self._send(500, f"error: {e}\n", "text/plain")
            except OSError:  # pragma: no cover - peer gone during the 500
                pass

    def log_message(self, fmt, *args):
        logger.debug("metrics http: " + fmt, *args)


class MetricsHTTPServer:
    """Daemon-thread HTTP server for /metrics and /metrics.json plus
    pluggable views: each `add_view(name, fn)` registration serves the
    provider's result at `/<name>` (dicts as JSON, strings verbatim).
    The engine registers "status" and "trace"; planes that come and go
    (serving, future workloads) register and remove their own views
    instead of threading constructor kwargs through this module.
    `port=0` binds an ephemeral port (tests); read it back via `.port`."""

    def __init__(self, port: int,
                 registry: Optional[telemetry.MetricsRegistry] = None,
                 fleet: Optional[telemetry.FleetView] = None,
                 status_fn: Optional[Callable[[], dict]] = None,
                 addr: str = "127.0.0.1",
                 trace_fn: Optional[Callable[[], str]] = None):
        self.registry = registry or telemetry.default_registry()
        self.fleet = fleet
        self._views: dict = {}
        self._views_lock = threading.Lock()
        # Constructor sugar kept for the two original views.
        if status_fn is not None:
            self.add_view("status", status_fn)
        if trace_fn is not None:
            self.add_view("trace", trace_fn)
        self._httpd = ThreadingHTTPServer((addr, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.owner = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-metrics-http",
            daemon=True,
        )

    # -- pluggable views -------------------------------------------------
    def add_view(self, name: str, fn: Callable[[], object]
                 ) -> "MetricsHTTPServer":
        """Serve `fn()` at `/<name>`. Reserved names (the metrics
        renderers) are rejected; re-registering a name replaces the
        previous provider (latest owner wins, like Gauge.set_function)."""
        if not name or not all(c.isalnum() or c in "_-" for c in name):
            raise ValueError(f"invalid view name {name!r}")
        # "metrics.json" needs no reservation: dots already fail the
        # charset check above.
        if name == "metrics":
            raise ValueError(f"view name {name!r} is reserved")
        with self._views_lock:
            self._views[name] = fn
        return self

    def remove_view(self, name: str, fn: Optional[Callable] = None):
        """Detach a view — the teardown contract for owners going away.
        Pass the provider you registered to detach only if you are still
        the current owner (a replacement may have taken the name over);
        None detaches unconditionally."""
        with self._views_lock:
            if fn is None or self._views.get(name) == fn:
                self._views.pop(name, None)

    def get_view(self, name: str) -> Optional[Callable[[], object]]:
        with self._views_lock:
            return self._views.get(name)

    def view_names(self) -> list:
        with self._views_lock:
            return sorted(self._views)

    def start(self) -> "MetricsHTTPServer":
        self._thread.start()
        logger.info("metrics endpoint serving on :%d (/metrics, /status)",
                    self.port)
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# Env-driven lifecycle (wired from Engine.start on rank 0 / every rank
# for the file dump; see engine/engine.py).

def start_exporters_from_env(
    registry: Optional[telemetry.MetricsRegistry] = None,
    fleet: Optional[telemetry.FleetView] = None,
    status_fn: Optional[Callable[[], dict]] = None,
    rank: int = 0,
    trace_fn: Optional[Callable[[], str]] = None,
):
    """Start the exporters the environment asks for. Returns a list of
    started exporter objects (each has .stop()). The HTTP endpoint only
    starts on rank 0 — it serves the fleet view; the JSON file dump runs
    on rank 0 too unless the path contains `{rank}` (then every rank
    writes its own file)."""
    started = []
    path = env_cfg.get_str(env_cfg.METRICS_FILE)
    if path and (rank == 0 or "{rank}" in path):
        # Interval <= 0 disables, matching HOROVOD_METRICS_SYNC_SECONDS
        # (not "dump as fast as possible").
        interval = env_cfg.get_float(env_cfg.METRICS_FILE_INTERVAL, 30.0)
        if interval > 0:
            started.append(MetricsFileWriter(
                path, registry, fleet, interval=interval, rank=rank
            ).start())
    port = env_cfg.get_int(env_cfg.METRICS_PORT, -1)
    if port >= 0 and rank == 0:
        # Loopback by default: the endpoint is unauthenticated, so
        # network exposure (remote Prometheus scrapers) is the explicit
        # opt-in, matching the rendezvous server's HMAC-everything
        # posture.
        addr = env_cfg.get_str(env_cfg.METRICS_ADDR, "127.0.0.1")
        try:
            started.append(MetricsHTTPServer(
                port, registry, fleet, status_fn=status_fn, addr=addr,
                trace_fn=trace_fn,
            ).start())
        except OSError as e:
            logger.warning("metrics endpoint on port %d failed to start: %s",
                           port, e)
    return started
