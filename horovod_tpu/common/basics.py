"""Global runtime state + init/shutdown/rank/size introspection.

TPU-native re-design of the reference's HorovodBasics ctypes layer
(ref: horovod/common/basics.py:22-233) and the C-side InitializeHorovodOnce
(ref: horovod/common/operations.cc:620-666).

Two execution modes:

* **mesh mode** (default, single-controller SPMD): `init()` builds a 1-D
  `jax.sharding.Mesh` over every visible chip. `size()` is the number of
  chips in the data axis; collectives called inside `jit`/`shard_map`
  lower to XLA collectives over ICI. This is the idiomatic TPU shape of
  "one rank per accelerator": XLA *is* the communication engine, so the
  reference's background negotiation thread is unnecessary — the static
  op set under jit plays the role of a 100%-hit response cache
  (ref: controller.cc:174-203 fast path).

* **process mode** (launched by `hvdrun`, detected via HOROVOD_RANK env;
  ref env contract: horovod/runner/gloo_run.py:65-198): classic
  one-process-per-rank SPMD with the asynchronous name-negotiated engine
  (horovod_tpu.engine) over a TCP full-mesh backend — the Gloo-equivalent
  control+data plane — or over XLA collectives when each process owns
  TPU chips (multi-host).
"""
from __future__ import annotations

import atexit
import os
import threading
from typing import List, Optional, Sequence

from ..utils import env as env_cfg
from ..utils.logging import get_logger
from . import telemetry
from .exceptions import NotInitializedError
from .types import ReduceOp

logger = get_logger()


class _State:
    def __init__(self):
        self.initialized = False
        self.mode: Optional[str] = None  # "mesh" | "process"
        self.rank = 0
        self.size = 1
        self.local_rank = 0
        self.local_size = 1
        self.cross_rank = 0
        self.cross_size = 1
        self.mesh = None
        self.axis_name = None
        self.engine = None
        self.ranks: Optional[List[int]] = None  # subset init (ref: basics.py:33-65)
        self.exporters: List = []  # mesh-mode metrics exporters
        self.lock = threading.Lock()


_state = _State()

# Incarnation counters for subset communicators, keyed by the member-rank
# tuple. Survives shutdown() so re-inits scope fresh rendezvous keys.
_subset_generations: dict = {}


def _init_mesh_mode(devices=None, axis_name: str = "hvd"):
    import jax

    from ..parallel.mesh import data_parallel_mesh

    _state.mesh = data_parallel_mesh(devices, axis_name)
    _state.axis_name = axis_name
    _state.mode = "mesh"
    _state.size = _state.mesh.size
    _state.rank = jax.process_index()
    _state.local_rank = 0
    _state.local_size = jax.local_device_count()
    _state.cross_rank = jax.process_index()
    _state.cross_size = jax.process_count()


def _init_process_mode(ranks: Optional[Sequence[int]] = None):
    from ..engine.engine import Engine

    _state.mode = "process"
    world_rank = env_cfg.get_int(env_cfg.RANK, 0)
    _state.rank = world_rank
    _state.size = env_cfg.get_int(env_cfg.SIZE, 1)
    _state.local_rank = env_cfg.get_int(env_cfg.LOCAL_RANK, 0)
    _state.local_size = env_cfg.get_int(env_cfg.LOCAL_SIZE, 1)
    _state.cross_rank = env_cfg.get_int(env_cfg.CROSS_RANK, 0)
    _state.cross_size = env_cfg.get_int(env_cfg.CROSS_SIZE, 1)
    scope = None
    if ranks is not None:
        # Subset communicator (ref: basics.py:33-65 — init(comm) with a
        # sub-communicator; only member processes may call init). Ranks
        # are renumbered 0..len-1 in the given order and the subset
        # rendezvouses under its own mesh scope so it never collides
        # with the world mesh or other subsets.
        ranks = [int(r) for r in ranks]
        if world_rank not in ranks:
            raise ValueError(
                f"process {world_rank} is not a member of the "
                f"communicator ranks={ranks}; only members may init"
            )
        _state.ranks = ranks
        _state.rank = ranks.index(world_rank)
        _state.size = len(ranks)
        base = env_cfg.get_str(env_cfg.MESH_SCOPE, "hvd_mesh")
        # Scope includes an incarnation counter: members re-init subsets
        # in lockstep, so shutdown+init of the same ranks gets fresh KV
        # keys instead of reading a peer's stale host:port from the
        # previous incarnation (the elastic path epoch-scopes MESH_SCOPE
        # for the same reason). Caveat: the counter is per-process, so a
        # freshly respawned member (gen 0) cannot rejoin survivors at
        # gen>0 — recovery across process death must go through the
        # elastic driver, whose epoch-scoped MESH_SCOPE resets every
        # member's world AND subset scopes together.
        gen = _subset_generations.get(tuple(ranks), 0)
        _subset_generations[tuple(ranks)] = gen + 1
        scope = f"{base}_ps_{'_'.join(map(str, ranks))}_g{gen}"
    _state.engine = Engine(
        rank=_state.rank,
        size=_state.size,
        local_rank=_state.local_rank,
        local_size=_state.local_size,
        cross_rank=_state.cross_rank,
        cross_size=_state.cross_size,
        scope=scope,
    )
    _state.engine.start()


def init(ranks: Optional[Sequence[int]] = None, devices=None, axis_name: str = "hvd",
         mode: Optional[str] = None):
    """Initialize the runtime (ref: horovod/common/basics.py:33-65).

    `ranks`: optional subset of ranks forming the communicator (process
    mode only). `mode`: force "mesh" or "process"; by default process
    mode is selected when the launcher's HOROVOD_RANK env is present.
    """
    with _state.lock:
        if _state.initialized:
            return
        if mode is None:
            mode = "process" if os.environ.get(env_cfg.RANK) is not None else "mesh"
        if mode == "process":
            _init_process_mode(ranks)
        else:
            if ranks is not None and devices is None:
                import jax

                all_devices = jax.devices()
                devices = [all_devices[r] for r in ranks]
            _init_mesh_mode(devices, axis_name)
            # Process mode's engine owns its exporters; mesh mode has no
            # engine, so the env-driven exporters start here (registry
            # only — there is no negotiation state to report).
            from . import metrics_export

            _state.exporters = metrics_export.start_exporters_from_env(
                status_fn=lambda: {
                    "rank": _state.rank,
                    "size": _state.size,
                    "mode": _state.mode,
                },
                rank=_state.rank,
            )
            # Events plane (docs/events.md): arm the recorder (spool
            # env included) and serve the local ring at /events — mesh
            # mode has no engine to do either.
            from . import events as events_mod

            events_mod.current(rank=_state.rank)
            events_mod.set_rank(_state.rank)
            for exp in _state.exporters:
                if isinstance(exp, metrics_export.MetricsHTTPServer):
                    exp.add_view("events", events_mod.local_view)
        _state.initialized = True
        # Baseline gauge for "world shrank" alerts — set on EVERY init,
        # not only after an elastic reset (elastic/run.py updates it too).
        telemetry.gauge(
            "horovod_world_size", "World size after the last (re)init"
        ).set(_state.size)
        # Build identity + uptime on the process registry: every scrape
        # (and the perf regression reporter) can attribute numbers to a
        # build (docs/health.md).
        telemetry.register_build_info()
        # Goodput ledger (docs/goodput.md): ensure the process ledger
        # exists in BOTH modes — mesh mode has no engine to create it,
        # and the optimizer's auto-step hook only feeds a live ledger.
        # Rank is passed explicitly: mesh mode is selected precisely
        # when HOROVOD_RANK is absent, so the env default would make
        # every multi-host mesh process a rank-0 stamp owner.
        from . import goodput

        goodput.current(rank=_state.rank)
        # Drain plane (docs/fault_tolerance.md "Announced preemption"):
        # spawned workers get the preemption-signal handler on init, so
        # an intentional stop (the launcher's teardown SIGTERM, a spot
        # preemption notice) exits 0 instead of dying on the signal and
        # being attributed as a failure. The elastic run loop upgrades
        # to managed mode (drain at a commit boundary); user processes
        # without the launcher env are left untouched.
        if os.environ.get(env_cfg.RANK) is not None \
                or os.environ.get(env_cfg.ELASTIC) is not None:
            from . import drain

            drain.coordinator.install()
        logger.debug(
            "horovod_tpu initialized: mode=%s rank=%d size=%d local=%d/%d cross=%d/%d",
            _state.mode, _state.rank, _state.size, _state.local_rank,
            _state.local_size, _state.cross_rank, _state.cross_size,
        )


def shutdown():
    """(ref: horovod/common/basics.py:74-80 → operations.cc horovod_shutdown)"""
    with _state.lock:
        if not _state.initialized:
            return
        if _state.engine is not None:
            _state.engine.shutdown()
            _state.engine = None
        # The elastic notify server + epoch watcher would otherwise
        # survive (and accumulate across) init/shutdown cycles; the
        # elastic run loop re-inits the manager after each reset.
        from ..backend.elastic_env import notification_manager

        notification_manager.shutdown()
        for exp in _state.exporters:
            try:
                exp.stop()
            except Exception:  # pragma: no cover - exporter already dead
                pass
        _state.exporters = []
        _state.mesh = None
        _state.initialized = False
        _state.mode = None


atexit.register(shutdown)


def is_initialized() -> bool:
    """(ref: horovod/common/basics.py:82-86)"""
    return _state.initialized


def _require_init():
    if not _state.initialized:
        raise NotInitializedError()


def rank() -> int:
    """Global rank (ref: basics.py:120-133).

    Mesh mode: the controlling process's index (0 on a single host)."""
    _require_init()
    return _state.rank


def size() -> int:
    """World size (ref: basics.py:148-160). Mesh mode: number of chips in
    the data-parallel mesh — one rank per accelerator, TPU-style."""
    _require_init()
    return _state.size


def local_rank() -> int:
    """(ref: basics.py:135-146)"""
    _require_init()
    return _state.local_rank


def local_size() -> int:
    """(ref: basics.py:162-172)"""
    _require_init()
    return _state.local_size


def cross_rank() -> int:
    _require_init()
    return _state.cross_rank


def cross_size() -> int:
    _require_init()
    return _state.cross_size


def is_homogeneous() -> bool:
    """(ref: mpi_controller.cc:26-82 homogeneity check)"""
    _require_init()
    return _state.size % _state.cross_size == 0


def mesh():
    """The active device mesh (mesh mode) or None (process mode)."""
    _require_init()
    return _state.mesh


def axis_name() -> Optional[str]:
    _require_init()
    return _state.axis_name


def mode() -> str:
    _require_init()
    return _state.mode


def engine():
    _require_init()
    return _state.engine


def metrics() -> dict:
    """Snapshot of the telemetry registry (docs/metrics.md).

    Returns ``{"rank", "size", "mode", "metrics", "status"?, "fleet"?}``:
    `metrics` is the flat name → value dict (histograms as
    {count,sum,bounds,counts}); in process mode `status` is the live
    engine state (queue depth, pending tensors, last-cycle age) and, on
    rank 0, `fleet` is the cross-rank per-rank/min/max/sum view. Usable
    before init too — module-level counters (retries, faults) exist
    regardless."""
    eng = _state.engine
    reg = eng.registry if eng is not None else telemetry.default_registry()
    out = {
        "rank": _state.rank,
        "size": _state.size,
        "mode": _state.mode,
        "metrics": reg.snapshot(),
    }
    if eng is not None:
        status = eng.status()
        # One fleet snapshot, hoisted to the top level (status() embeds
        # it for the /status endpoint; two separate snapshots here could
        # disagree within one result).
        fleet = status.pop("fleet", None)
        out["status"] = status
        if fleet is not None:
            out["fleet"] = fleet
    return out


# Capability introspection (ref: basics.py:174-208 mpi_built/nccl_built...).
def xla_built() -> bool:
    return True


def tcp_built() -> bool:
    return True


def mpi_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def gloo_built() -> bool:
    # The TCP backend is the Gloo-equivalent.
    return True


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    # TPU framework: device compute goes through XLA, never CUDA
    # (ref: horovod/torch/mpi_ops.py cuda_built).
    return False


def rocm_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_enabled() -> bool:
    # The TCP controller + engine fill Gloo's role (see gloo_built).
    return True


def mpi_threads_supported() -> bool:
    return False


def gpu_available(ext_base_name: str = "") -> bool:
    """TPU chips are not GPUs; GPU-specific paths are never taken
    (ref: horovod/common/util.py gpu_available)."""
    return False


def check_extension(ext_name: str, *args, **kwargs) -> None:
    """All framework adapters are pure-Python over the shared engine —
    there is no compiled per-framework extension that could be missing
    (ref: horovod/common/util.py check_extension raises when the
    framework .so wasn't built)."""
    return None


def num_rank_is_power_2(num_rank: int) -> bool:
    """(ref: horovod/common/util.py num_rank_is_power_2 — Adasum's
    ladder needs a power-of-2 world.)"""
    return num_rank != 0 and (num_rank & (num_rank - 1)) == 0


def check_num_rank_power_of_2(num_rank: int) -> None:
    if not num_rank_is_power_2(num_rank):
        raise ValueError(
            "Adasum requires a power-of-2 number of ranks; got "
            f"{num_rank}"
        )
