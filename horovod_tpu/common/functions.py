"""Object/parameter broadcast + allgather helpers.

(ref: horovod/torch/functions.py:30-262 — broadcast_parameters,
broadcast_optimizer_state, broadcast_object, allgather_object;
horovod/tensorflow/functions.py:47-160.)
"""
from __future__ import annotations

import io
import pickle
from typing import Any, List, Optional

import numpy as np

from . import basics
from .types import ReduceOp


def _bcast_leaf(x, root_rank: int):
    from .. import ops

    arr = np.asarray(x)
    out = ops.broadcast(arr, root_rank)
    try:
        import jax.numpy as jnp

        if not isinstance(x, np.ndarray):
            return jnp.asarray(np.asarray(out)).astype(arr.dtype).reshape(arr.shape)
    except ImportError:
        pass
    return np.asarray(out).reshape(arr.shape)


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a pytree of parameters from root to all ranks
    (ref: horovod/torch/functions.py:30-60 broadcast_parameters). Returns
    the synchronized pytree (functional, JAX-style — no in-place)."""
    import jax

    return jax.tree.map(lambda x: _bcast_leaf(x, root_rank), params)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """(ref: horovod/torch/functions.py:62-107) — optax states are
    pytrees, so this is the same traversal."""
    return broadcast_parameters(opt_state, root_rank)


def broadcast_object(obj: Any = None, root_rank: int = 0, name: Optional[str] = None):
    """Pickle + broadcast an arbitrary object
    (ref: horovod/torch/functions.py:186-227)."""
    from .. import ops

    if basics.size() == 1 or basics.mode() == "mesh":
        return obj
    if basics.rank() == root_rank:
        payload = pickle.dumps(obj)
        sz = np.array([len(payload)], dtype=np.int64)
    else:
        payload = b""
        sz = np.zeros(1, dtype=np.int64)
    nm = name or "broadcast_object"
    sz = np.asarray(ops.broadcast(sz, root_rank, name=f"{nm}.size"))
    buf = np.frombuffer(payload, dtype=np.uint8).copy() if payload else np.zeros(
        int(sz[0]), dtype=np.uint8
    )
    buf = np.asarray(ops.broadcast(buf, root_rank, name=f"{nm}.data"))
    return pickle.loads(buf.tobytes())


def allgather_object(obj: Any, name: Optional[str] = None) -> List[Any]:
    """(ref: horovod/torch/functions.py:229-262)"""
    from .. import ops

    if basics.size() == 1 or basics.mode() == "mesh":
        return [obj] * basics.size()
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    nm = name or "allgather_object"
    sizes = np.asarray(
        ops.allgather(np.array([payload.size], dtype=np.int64), name=f"{nm}.size")
    )
    data = np.asarray(ops.allgather(payload, name=f"{nm}.data"))
    out, off = [], 0
    for s in sizes.ravel():
        out.append(pickle.loads(data[off : off + int(s)].tobytes()))
        off += int(s)
    return out
