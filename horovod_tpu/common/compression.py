"""Gradient compression — the single-source core.

Two layers live here, deliberately in one file so they can never drift:

* the **framework `Compression` API** (ref: horovod/torch/compression.py:
  20-74, horovod/tensorflow/compression.py:46-64): tensor-level
  compress/decompress pairs applied by `DistributedOptimizer` wrappers
  BEFORE a tensor is enqueued. `ops/compression.py`,
  `tensorflow/compression.py` and `torch/compression.py` are thin
  re-exports of the interface plus their tensor-type adapters (jnp / tf
  / torch casts) — the same single-source treatment PR 8 gave
  `base.desync_message`.

* the **wire codec layer** (docs/running.md "Wire compression"): numpy
  byte-level codecs the collective data plane applies to the frames it
  actually ships — ring segments, star gather/bcast payloads, shm arena
  deposits. Unlike the framework API (which converts the tensor the
  engine then carries end-to-end), a wire codec halves the bytes ON THE
  WIRE while the engine, the reduction arithmetic and the user-visible
  result stay full-width fp32. The coordinator picks a codec per
  `Response` (engine/controller.py `_assign_codecs`) and carries its id
  in the wire message next to the PR 4 channel id, so the choice is
  collectively agreed and cache-replay-stable by construction.

Error feedback (`ErrorFeedback` below) is the accuracy device: each
rank keeps a per-tensor residual, adds it to the gradient before
encoding, and stores the new residual = pre-encode value minus the
decoded wire value — the construction of 1-bit SGD (Seide et al. 2014)
formalized by Karimireddy et al. 2019 ("Error Feedback Fixes
SignSGD"): the quantization error is not lost, it is re-injected into
the next step, so compressed SGD converges to the uncompressed
optimum.

Rank-consistency contract: the engine projects every contribution onto
the codec grid (decode∘encode) BEFORE the collective runs, and every
data-plane path that ships a full-width-held value compressed
re-projects it on the sending side (ring allgather owners, star root),
so all ranks finish a collective holding bitwise-identical results —
the same determinism the uncompressed planes guarantee.
"""
from __future__ import annotations

import collections
import queue as _queue
import struct
import threading
from typing import Dict, Optional

import numpy as np

from ..cc import native as _native

# ---------------------------------------------------------------------------
# framework Compression API (interface + identity; adapters live in the
# framework modules)


class Compressor:
    """Interface for framework-level gradient compression
    (ref: compression.py:24-35)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity compressor (ref: compression.py NoneCompressor) —
    framework-agnostic, so every binding shares this one."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


# ---------------------------------------------------------------------------
# wire codecs (data-plane, numpy)

CODEC_NONE = 0
CODEC_BF16 = 1
CODEC_FP16 = 2
CODEC_INT8 = 3

_SCALE = struct.Struct("<f")

# ml_dtypes (a jax dependency) implements bfloat16 as a native numpy
# dtype: one C cast pass each way, ~4x faster than the pure-numpy bit
# path below and bit-identical to it (round-to-nearest-even, NaN
# preserved — asserted by the codec property tests). The bit path is
# the no-dependency fallback, so the codec layer never *requires*
# anything beyond numpy.
try:
    import ml_dtypes as _ml_dtypes

    _BF16_DTYPE = np.dtype(_ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - jax images ship ml_dtypes
    _BF16_DTYPE = None


class WireCodec:
    """One on-wire encoding: fp32 elements -> wire bytes and back.

    ``encode`` returns a flat uint8 array (scale headers included for
    variable-width codecs); ``decode`` reconstructs fp32 from any
    buffer-protocol object. ``wire_itemsize`` is the fixed bytes per
    element, or None for codecs with a per-tensor header (int8+scale)
    — the ring and the arena slice frames/slots by element offsets,
    so they only engage fixed-width codecs; the star path (whole
    tensors per frame) handles all of them.
    """

    id = CODEC_NONE
    name = "none"
    wire_itemsize: Optional[int] = None

    def applicable(self, dtype) -> bool:
        """Wire codecs narrow fp32 payloads; everything else ships
        full-width. dtype is negotiated, so the gate is collectively
        consistent."""
        return np.dtype(dtype) == np.float32

    def wire_bytes(self, count: int) -> int:
        raise NotImplementedError

    def encode(self, arr: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def decode(self, buf, count: int) -> np.ndarray:
        raise NotImplementedError

    def roundtrip(self, arr: np.ndarray) -> np.ndarray:
        """Project onto the codec grid: decode(encode(arr))."""
        return self.decode(self.encode(arr), int(np.asarray(arr).size))


class Bf16Codec(WireCodec):
    """bfloat16 on the wire — the TPU-native reduced type: same 8-bit
    exponent as fp32 (no overflow on gradients), 7 mantissa bits.
    Encode is a round-to-nearest-even narrowing of the fp32 high half,
    decode a widening — one C cast pass each way via ml_dtypes when
    present, else vectorized numpy bit manipulation (bit-identical,
    ~4x slower; numpy has no native bf16)."""

    id = CODEC_BF16
    name = "bf16"
    wire_itemsize = 2

    def wire_bytes(self, count: int) -> int:
        return 2 * count

    def encode(self, arr: np.ndarray) -> np.ndarray:
        a = np.ascontiguousarray(arr, np.float32).reshape(-1)
        out = _native.bf16_encode(a)  # GIL-free, bit-identical
        if out is not None:
            return out
        if _BF16_DTYPE is not None:
            return a.astype(_BF16_DTYPE).view(np.uint8)
        u = a.view(np.uint32)
        lsb = (u >> np.uint32(16)) & np.uint32(1)
        out = ((u + np.uint32(0x7FFF) + lsb) >> np.uint32(16)).astype(
            np.uint16)
        special = (u & np.uint32(0x7F800000)) == np.uint32(0x7F800000)
        if special.any():
            # inf/nan: truncate (rounding could wrap a NaN payload into
            # +0) and keep NaNs NaN by pinning a mantissa bit.
            t = (u >> np.uint32(16)).astype(np.uint16)
            nan = special & ((u & np.uint32(0x007FFFFF)) != 0)
            t = np.where(nan, t | np.uint16(0x0040), t)
            out = np.where(special, t, out)
        return out.view(np.uint8)

    def decode(self, buf, count: int) -> np.ndarray:
        out = _native.bf16_decode(buf, count)
        if out is not None:
            return out
        if _BF16_DTYPE is not None:
            return np.frombuffer(
                buf, dtype=_BF16_DTYPE, count=count).astype(np.float32)
        u16 = np.frombuffer(buf, dtype=np.uint16, count=count)
        return (u16.astype(np.uint32) << np.uint32(16)).view(np.float32)


class Fp16Codec(WireCodec):
    """IEEE fp16 on the wire: 10 mantissa bits (finer than bf16) but a
    5-bit exponent — values past ~65504 saturate to inf. numpy-native
    casts both ways."""

    id = CODEC_FP16
    name = "fp16"
    wire_itemsize = 2

    def wire_bytes(self, count: int) -> int:
        return 2 * count

    def encode(self, arr: np.ndarray) -> np.ndarray:
        a = np.ascontiguousarray(arr, np.float32).reshape(-1)
        out = _native.fp16_encode(a)  # GIL-free, bit-identical
        if out is not None:
            return out
        with np.errstate(over="ignore"):  # >65504 saturates to inf
            return a.astype(np.float16).view(np.uint8)

    def decode(self, buf, count: int) -> np.ndarray:
        out = _native.fp16_decode(buf, count)
        if out is not None:
            return out
        return np.frombuffer(
            buf, dtype=np.float16, count=count).astype(np.float32)


class Int8Codec(WireCodec):
    """Linear int8 quantization with one per-encode fp32 scale carried
    as a 4-byte payload header (wire cost: count + 4 bytes — 4x fewer
    than fp32 for anything non-trivial). scale = max|finite value|/127;
    non-finite inputs clip to the extremes (the error-feedback residual
    keeps what quantization drops). Opt-in for the latency channel —
    small control-ish tensors where 4x on a ~latency-bound frame
    matters and coarse quantization is tolerable."""

    id = CODEC_INT8
    name = "int8"
    wire_itemsize = None  # variable (scale header): star path only

    def wire_bytes(self, count: int) -> int:
        return count + _SCALE.size

    def encode(self, arr: np.ndarray) -> np.ndarray:
        a = np.ascontiguousarray(arr, np.float32).reshape(-1)
        out = _native.int8_encode(a)  # GIL-free, bit-identical
        if out is not None:
            return out
        scale = 0.0
        if a.size:
            finite = a[np.isfinite(a)]
            if finite.size:
                scale = float(np.max(np.abs(finite))) / 127.0
        if not np.isfinite(scale) or scale <= 0.0:
            scale = 0.0
        out = np.empty(_SCALE.size + a.size, np.uint8)
        out[:_SCALE.size] = np.frombuffer(_SCALE.pack(scale), np.uint8)
        if scale:
            q = np.clip(np.round(a / np.float32(scale)), -127, 127)
            q = np.nan_to_num(q, nan=0.0, posinf=127.0, neginf=-127.0)
            out[_SCALE.size:] = q.astype(np.int8).view(np.uint8)
        else:
            out[_SCALE.size:] = 0
        return out

    def decode(self, buf, count: int) -> np.ndarray:
        out = _native.int8_decode(buf, count)
        if out is not None:
            return out
        view = memoryview(buf)
        (scale,) = _SCALE.unpack(bytes(view[:_SCALE.size]))
        q = np.frombuffer(view, dtype=np.int8, count=count,
                          offset=_SCALE.size)
        return q.astype(np.float32) * np.float32(scale)


_CODECS_BY_ID: Dict[int, WireCodec] = {
    c.id: c for c in (Bf16Codec(), Fp16Codec(), Int8Codec())
}
_CODECS_BY_NAME: Dict[str, WireCodec] = {
    c.name: c for c in _CODECS_BY_ID.values()
}


def codec_by_id(codec_id: int) -> Optional[WireCodec]:
    """Resolve a wire-carried codec id; 0/unknown -> None (ship
    full-width — an unknown id from a newer coordinator degrades to
    uncompressed rather than desyncing, because the id is collectively
    agreed so every rank degrades identically)."""
    return _CODECS_BY_ID.get(codec_id)


def codec_by_name(name: str) -> Optional[WireCodec]:
    return _CODECS_BY_NAME.get(name)


# ---------------------------------------------------------------------------
# error feedback


class ErrorFeedback:
    """Per-(tensor-name) quantization residuals for one engine/rank.

    Lifecycle: owned by the Engine, so an elastic reset (which builds a
    fresh Engine) starts from zero residuals on every rank at the same
    step — the consistency the reset protocol needs. Cache-replayed
    responses carry the same tensor names in the same order on every
    rank, so the keys line up across ranks without any coordination. A
    residual whose size no longer matches (re-negotiated shape; the
    response cache invalidates in the same cycle on every rank) is
    dropped rather than misapplied.

    Capacity: the store holds at most ``capacity`` residuals (default
    matching the response cache's 1024), evicting the least recently
    updated — a workload enqueueing uniquely-named allreduces (or a
    fusion regrouping churning the joined-name keys) must leak
    warnings' worth of accuracy, never unbounded full-width fp32
    buffers. An evicted steady-state tensor simply restarts error
    feedback from a zero residual, exactly like a fresh engine.

    Thread model: response keys are disjoint across channel executors
    (one response runs on one channel at a time); the recency
    bookkeeping shares one lock — one acquire per op, noise next to
    the multi-MB codec passes it brackets.
    """

    # Mirrors DEFAULT_CACHE_CAPACITY (utils/env.py): residual keys are
    # response-cache keys (joined tensor names), so the two populations
    # are the same order of magnitude in steady state.
    DEFAULT_CAPACITY = 1024

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(int(capacity), 1)
        self._store: "collections.OrderedDict[str, np.ndarray]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def get(self, key: str, size: int) -> Optional[np.ndarray]:
        with self._lock:
            r = self._store.get(key)
        return r if r is not None and r.size == size else None

    def put(self, key: str, residual: np.ndarray) -> None:
        # Quantizer saturation defense: fp16 overflows finite inputs
        # to inf, making residual = pre - inf = -inf; next round
        # pre + (-inf) is -inf and the round after that NaN — a
        # permanently poisoned tensor from one out-of-range gradient.
        # A saturated lane's difference is meaningless anyway, so a
        # non-finite residual entry resets to 0 (the wire value still
        # carries the inf/NaN to the user for THIS round).
        if not np.isfinite(residual).all():
            residual = np.nan_to_num(residual, nan=0.0, posinf=0.0,
                                     neginf=0.0)
        with self._lock:
            self._store[key] = residual
            self._store.move_to_end(key)
            while len(self._store) > self.capacity:
                self._store.popitem(last=False)

    def update(self, key: str, pre: np.ndarray,
               wire: np.ndarray) -> None:
        """Store residual = pre - wire, reusing the previous residual's
        buffer when shapes match (a 16MB np.subtract into fresh memory
        pays page-fault cost every step; the residual is dead the
        moment the new one is computed, so it is the natural scratch).
        ``pre`` may alias the old residual's CONSUMER (the engine adds
        the residual into the gradient buffer, not into the residual),
        never the residual itself, so the in-place write is safe."""
        with self._lock:
            old = self._store.get(key)
        if old is not None and old.size == pre.size \
                and old.dtype == pre.dtype:
            # Native pass fuses subtract + saturation defense in one
            # GIL-free sweep (cc/core.cc hvd_ef_update).
            if not _native.ef_update(old, pre, wire):
                np.subtract(pre, wire, out=old)
                if not np.isfinite(old).all():  # see put()
                    np.nan_to_num(old, copy=False, nan=0.0, posinf=0.0,
                                  neginf=0.0)
            with self._lock:
                self._store[key] = old
                self._store.move_to_end(key)
        else:
            self.put(key, pre - wire)

    def drop(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    def reset(self) -> None:
        with self._lock:
            self._store.clear()

    def size(self) -> int:
        return len(self._store)

    def nbytes(self) -> int:
        with self._lock:
            return sum(int(r.nbytes) for r in self._store.values())


# ---------------------------------------------------------------------------
# codec/wire overlap pipeline (docs/running.md "Wire compression")


class StageFuture:
    """Completion handle for one PipelineStage job: ``result()`` blocks
    until the job ran and returns its value, re-raising the job's
    exception on the caller's thread."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None

    def _done(self, value=None, error: Optional[BaseException] = None):
        self._value = value
        self._error = error
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("pipeline stage job did not complete")
        if self._error is not None:
            raise self._error
        return self._value


_STAGE_STOP = object()


class PipelineStage:
    """Bounded SINGLE-worker pipeline stage for codec/wire overlap.

    Jobs run strictly FIFO on one worker thread — the property that
    lets the segmented ring hand encoded segments to the transport
    from a worker without breaking the per-channel FIFO contract —
    while the bounded queue keeps at most ``depth`` jobs (one encoded
    segment each) in flight, so a fast producer can never balloon
    memory. One stage per direction: the ring's encode stage encodes
    segment k+1 and ships it while segment k is on the wire; its
    decode stage decodes-and-reduces segment k-1 while the caller
    receives segment k. A job's exception parks in its future and
    re-raises at ``result()``; later jobs still run (the caller owns
    error propagation at its wait points, exactly like send tickets).

    Lifetime is one collective: created at phase start, ``stop()``-ed
    (sentinel + join) in the caller's finally — no backend shutdown
    plumbing, nothing to leak across elastic engine rebuilds.
    """

    def __init__(self, name: str, depth: int = 4):
        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(int(depth), 1))
        self._thread = threading.Thread(
            target=self._loop, name=f"hvd-{name}", daemon=True)
        self._thread.start()

    def submit(self, fn) -> StageFuture:
        fut = StageFuture()
        self._q.put((fn, fut))
        return fut

    def _loop(self):
        while True:
            item = self._q.get()
            if item is _STAGE_STOP:
                return
            fn, fut = item
            try:
                fut._done(fn())
            except BaseException as e:  # noqa: BLE001 - parked in future
                fut._done(error=e)

    def stop(self):
        self._q.put(_STAGE_STOP)
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


# ---------------------------------------------------------------------------
# telemetry sink


class CompressionStats:
    """The wire-compression telemetry sink the engine threads through
    the codec scope (backend/base.py wire_codec_scope) so every
    data-plane encode site — ring segments, star frames, arena
    deposits — counts into the SAME per-engine registry:

    * ``horovod_wire_bytes_saved_total{codec=}`` — wire bytes NOT
      moved thanks to the codec, counted per frame actually handed to
      a transport (a star root's broadcast counts once per peer; its
      own local contribution never counts — the number is wire truth,
      not an estimate);
    * ``horovod_compression_seconds{phase=}`` — encode / decode /
      feedback (the engine's error-feedback projection) latency.
    """

    def __init__(self, registry=None):
        from . import telemetry

        self._registry = (registry if registry is not None
                          else telemetry.default_registry())
        self._saved: Dict[str, object] = {}
        self._seconds: Dict[str, object] = {}
        self._lock = threading.Lock()

    def saved(self, codec_name: str, nbytes: int) -> None:
        c = self._saved.get(codec_name)
        if c is None:
            with self._lock:
                c = self._saved.get(codec_name)
                if c is None:
                    c = self._registry.counter(
                        "horovod_wire_bytes_saved_total",
                        "Wire bytes not moved thanks to on-wire "
                        "compression (per transport frame)",
                        labels={"codec": codec_name})
                    self._saved[codec_name] = c
        c.inc(nbytes)

    def observe(self, phase: str, seconds: float) -> None:
        h = self._seconds.get(phase)
        if h is None:
            with self._lock:
                h = self._seconds.get(phase)
                if h is None:
                    h = self._registry.histogram(
                        "horovod_compression_seconds",
                        "Wire codec encode/decode latency by phase",
                        labels={"phase": phase})
                    self._seconds[phase] = h
        h.observe(seconds)

    def saved_snapshot(self) -> Dict[str, float]:
        # Under the lock: a /status scrape iterating here races the
        # first compressed op of a new codec inserting its counter.
        with self._lock:
            return {name: c.value for name, c in self._saved.items()}
