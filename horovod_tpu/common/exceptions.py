"""Framework exceptions (ref: horovod/common/exceptions.py:17-31)."""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective fails.

    In elastic mode this triggers state restore + re-initialization
    (ref: horovod/common/exceptions.py:17-22)."""


class TransportError(HorovodInternalError):
    """A data/control-plane transport failure (peer died, socket timed
    out, rendezvous unreachable past retries). Subclass of
    HorovodInternalError so the elastic run loop's catch — and every
    public API contract — sees exactly the collective-failure signal;
    the distinct type lets tests and tooling assert the *transport*
    layer did the translating (no raw ConnectionError may escape).

    Attribution fields (docs/fault_tolerance.md "Root-cause
    attribution"): `peer` is the rank whose link failed, `reporter` the
    rank that observed it, `phase` the collective being executed when it
    surfaced (set by the engine), and `root_cause` the liveness
    verdict when the failure was a heartbeat-detector declaration rather
    than a socket-level event. Together they turn "connection reset"
    into "rank 2 (host X) died in allreduce"."""

    def __init__(self, message: str, peer=None, reporter=None,
                 phase=None, root_cause=None):
        super().__init__(message)
        self.peer = peer
        self.reporter = reporter
        self.phase = phase
        self.root_cause = root_cause

    def __str__(self):
        base = super().__str__()
        if self.phase:
            return f"{base} (during {self.phase})"
        return base


class HostsUpdatedInterrupt(RuntimeError):
    """Raised when the set of hosts changed mid-training; the current batch
    result is still valid, so state is committed rather than restored
    (ref: horovod/common/exceptions.py:25-31)."""

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


class WorkerPreempted(SystemExit):
    """Raised on a draining worker once its graceful-drain work (final
    forced checkpoint, goodput stamp release, drain notice) is done —
    the announced-preemption exit (docs/fault_tolerance.md "Announced
    preemption"). A ``SystemExit`` subclass with code 0: the elastic run
    loop's cleanup (``finally``) still runs, user ``except Exception``
    blocks never swallow it, and the process exits cleanly so the
    launcher/driver records an intentional stop, not a failure."""

    def __init__(self, reason: str = "preempted"):
        super().__init__(0)
        self.reason = reason


class NotInitializedError(RuntimeError):
    def __init__(self, what: str = "Horovod-TPU"):
        super().__init__(
            f"{what} has not been initialized; call horovod_tpu.init() first."
        )


class TensorValidationError(ValueError):
    """Cross-rank tensor mismatch detected by the controller
    (ref: controller.cc:380-657 ConstructResponse error strings)."""
