"""Graceful-drain plane: announced preemptions as a first-class event
(docs/fault_tolerance.md "Announced preemption").

On spot/multi-tenant fleets the dominant disruption is not the silent
crash the liveness plane (common/health.py) exists to bound — it is the
*announced* preemption: the platform delivers SIGTERM (or a
provider-specific notice) and grants a grace window before the kill.
Reacting to that notice only after the rank dies wastes the window
twice: the failure-detection timeout burns wall-clock, and the steps
since the last interval checkpoint are replayed. This module turns the
notice into a coordinated drain instead:

1. **Notice** — the signal handler (installed for
   ``HOROVOD_PREEMPT_SIGNAL``, default SIGTERM) marks the drain
   requested, counts it, publishes a best-effort early notice into the
   rendezvous KV (``drain_e<epoch>/<identity>``) so the driver can
   quarantine the host immediately, and arms a hard deadline at
   ``HOROVOD_DRAIN_GRACE_SECONDS``.

2. **Barrier** — at the next ``state.commit()`` every rank allreduces a
   one-bit drain flag (``commit_barrier``), so the whole world learns of
   the drain at the *same* commit: all ranks force that commit durable
   together (``CheckpointManager.save_now`` — the coordinator's ack
   barrier needs the full world), survivors mark the fleet as draining
   (the coming re-mesh window is then attributed to the ``preemption``
   badput bucket, not ``failure``), and the draining rank proceeds to 3.

3. **Handoff** — the draining rank releases the goodput stamp
   (``goodput.release_stamp`` — ownership transfers to the promoted
   survivor via ``try_adopt_stamp``), publishes the final ``drained``
   notice, and leaves via ``WorkerPreempted`` — a ``SystemExit(0)``
   subclass, so the launcher/driver records an intentional stop. Its
   TCP FINs fail the survivors' next collective *immediately*; no
   heartbeat timeout is ever waited out.

If no commit boundary arrives inside the grace window, the deadline
timer exits the process cleanly anyway: at most one checkpoint interval
of steps is lost — exactly the unannounced-failure bound — and the
early notice already routed the attribution.

Outside an elastic run loop (``managed=False``, the launcher's static
teardown) the handler simply exits 0 promptly, so an intentional stop
is never attributed as a worker failure.

The coordinator is a process-wide singleton like
``fault_injection.injector``; the chaos harness's ``preempt`` rules
deliver the signal, so the whole path is drivable from tests and
``scripts/preemption_smoke.py`` without a real spot fleet.
"""
from __future__ import annotations

import json
import os
import signal as _signal
import threading
import time
from typing import Optional

from ..utils import env as env_cfg
from ..utils.logging import get_logger
from . import events as events_mod
from .exceptions import WorkerPreempted

logger = get_logger()

# KV layout: drain_e<epoch>/<host:spawn_local_rank> -> JSON notice doc,
# plus drain_e<epoch>/any -> marker (survivors + the liveness plane ask
# "is anyone draining this epoch?" without listing keys).
DRAIN_PREFIX = "drain_e"


def _m_preemptions():
    from . import telemetry

    return telemetry.counter(
        "horovod_preemptions_total",
        "Preemption notices (signal or chaos-injected) this worker "
        "received")


def _m_drain_seconds():
    from . import telemetry

    return telemetry.histogram(
        "horovod_drain_seconds",
        "Preemption notice to drained exit: final checkpoint durable, "
        "stamp released, notice published", min_exp=-4, max_exp=8)


class DrainCoordinator:
    """Per-process drain state machine (see module docstring).

    ``managed`` selects the two behaviours: an elastic run loop sets it
    (drain completes at a commit boundary, with checkpoint + handoff);
    unmanaged processes exit 0 straight from the handler. The flag must
    be UNIFORM across ranks — ``commit_barrier`` is a collective and
    every rank must agree whether to run it — which holds because only
    ``elastic.run_fn`` sets it, on every rank alike.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._requested = threading.Event()
        self._reason = ""
        self._t0: Optional[float] = None          # monotonic at notice
        self._deadline: Optional[threading.Timer] = None
        self._managed = False
        self._installed_signum: Optional[int] = None
        self._prev_handler = None
        # Freshest local evidence that a PEER is draining (set by the
        # commit barrier) — survivors consult it for badput attribution
        # without a KV round-trip.
        self._peer_mono: Optional[float] = None
        # Test seam: the hard exits (unmanaged notice, expired grace)
        # go through this so unit tests can observe instead of dying.
        self._exit = os._exit

    # -- lifecycle -----------------------------------------------------
    def install(self, managed: Optional[bool] = None) -> bool:
        """Register the preemption-signal handler (idempotent; main
        thread only — elsewhere the registration is skipped, which is
        fine for the in-process test harness where the chaos injector
        calls ``request()`` directly). A non-default handler some user
        code installed is never clobbered. Returns whether the handler
        is in place."""
        if managed is not None:
            with self._lock:
                self._managed = managed
        signum = env_cfg.preempt_signal()
        with self._lock:
            if self._installed_signum == signum:
                return True
        try:
            prev = _signal.getsignal(signum)
            if (prev not in (_signal.SIG_DFL, None)
                    and prev is not self._on_signal):
                logger.info(
                    "preemption signal %d already has a handler; leaving "
                    "it in place (graceful drain disabled)", signum)
                return False
            _signal.signal(signum, self._on_signal)
        except (ValueError, OSError):  # not the main thread / bad signum
            return False
        with self._lock:
            self._installed_signum = signum
            self._prev_handler = prev
        return True

    def set_managed(self, managed: bool):
        with self._lock:
            self._managed = managed

    def active(self) -> bool:
        """Whether the commit barrier should run (managed mode)."""
        return self._managed

    def pending(self) -> bool:
        return self._requested.is_set()

    @property
    def reason(self) -> str:
        return self._reason

    # -- the notice ----------------------------------------------------
    def _on_signal(self, signum, frame):  # pragma: no cover - exercised
        try:                              # via request() in tests
            name = _signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        self.request(f"signal {name}")

    def request(self, reason: str = "preemption notice"):
        """Mark the drain requested. Idempotent; callable from the
        signal handler, the chaos injector, or the controller path."""
        with self._lock:
            if self._requested.is_set():
                return
            self._requested.set()
            self._reason = reason
            self._t0 = time.monotonic()
            managed = self._managed
        _m_preemptions().inc()
        events_mod.emit(events_mod.DRAIN_NOTICE, severity=events_mod.WARN,
                        reason=reason, managed=managed)
        grace = env_cfg.drain_grace_seconds()
        if not managed:
            logger.warning(
                "preemption notice (%s) outside an elastic run loop: "
                "exiting cleanly now", reason)
            self._publish_notice("drained")
            self._exit(0)
            return
        logger.warning(
            "preemption notice (%s): draining — final checkpoint at the "
            "next commit, hard exit in %.0fs", reason, grace)
        # Publish EARLY (and off the handler's thread): the driver can
        # quarantine the host and survivors can attribute the coming
        # window even if this process never reaches another commit.
        threading.Thread(target=self._publish_notice, args=("requested",),
                         daemon=True, name="hvd-drain-notice").start()
        if grace > 0:
            t = threading.Timer(grace, self._grace_expired)
            t.daemon = True
            t.name = "hvd-drain-deadline"
            with self._lock:
                self._deadline = t
            t.start()

    def _grace_expired(self):
        logger.error(
            "drain grace (%.0fs) expired before a commit boundary; "
            "exiting without the final checkpoint — at most one "
            "checkpoint interval of steps is lost",
            env_cfg.drain_grace_seconds())
        self._publish_notice("drained")
        self._exit(0)

    def checkpoint_budget(self) -> float:
        """Wall budget left for the forced final checkpoint: the grace
        window minus elapsed, minus a margin for stamp release + exit."""
        grace = env_cfg.drain_grace_seconds()
        with self._lock:
            t0 = self._t0
        elapsed = 0.0 if t0 is None else time.monotonic() - t0
        return max(1.0, grace - elapsed - 2.0)

    # -- completion (draining rank, at a commit boundary) --------------
    def execute(self, state) -> None:
        """Complete the drain: the final checkpoint is already durable
        (``commit_barrier`` ran ``save_now`` on every rank first), so
        release the goodput stamp, publish the ``drained`` notice, and
        leave via ``WorkerPreempted``."""
        with self._lock:
            t, self._deadline = self._deadline, None
        if t is not None:
            t.cancel()
        from . import goodput

        goodput.release_stamp()
        self._publish_notice("drained")
        with self._lock:
            t0 = self._t0
        if t0 is not None:
            _m_drain_seconds().observe(time.monotonic() - t0)
        events_mod.emit(events_mod.DRAIN_DRAINED,
                        severity=events_mod.WARN, reason=self._reason)
        logger.warning("drained cleanly (%s); exiting", self._reason)
        raise WorkerPreempted(self._reason or "preempted")

    # -- survivor-side attribution -------------------------------------
    def note_peer_draining(self):
        if self._peer_mono is None:
            events_mod.emit(events_mod.DRAIN_PEER,
                            severity=events_mod.WARN)
        self._peer_mono = time.monotonic()

    def fleet_draining(self, window: float = 600.0) -> bool:
        """Whether this disruption should be attributed to the
        ``preemption`` bucket: this rank is draining, a peer announced
        a drain at a recent commit barrier, or the current epoch has a
        drain marker in the KV (covers a peer that died on its grace
        deadline without ever reaching a barrier)."""
        if self._requested.is_set():
            return True
        t = self._peer_mono
        if t is not None and time.monotonic() - t < window:
            return True
        return self._kv_marker_present()

    def _kv_marker_present(self) -> bool:
        try:
            kv = _kv_from_env()
            if kv is None:
                return False
            from ..backend import elastic_env

            epoch = elastic_env._current_epoch()
            if epoch is None:
                return False
            return kv.get(f"{DRAIN_PREFIX}{epoch}", "any") is not None
        except Exception:
            return False

    # -- KV notice -----------------------------------------------------
    def _publish_notice(self, phase: str):
        """Best-effort: a down rendezvous server must never stall (or
        fail) the drain itself."""
        try:
            kv = _kv_from_env()
            if kv is None:
                return
            from ..backend import elastic_env

            epoch = elastic_env._current_epoch()
            ident = elastic_env.spawn_identity()
            if epoch is None:
                return
            doc = {"identity": ident, "phase": phase,
                   "reason": self._reason, "wall": time.time()}
            from . import basics

            if basics.is_initialized():
                doc["rank"] = basics.rank()
            scope = f"{DRAIN_PREFIX}{epoch}"
            kv.put(scope, ident, json.dumps(doc).encode())
            kv.put(scope, "any",
                   json.dumps({"wall": doc["wall"],
                               "phase": phase}).encode())
        except Exception as e:
            logger.debug("drain notice publish failed: %s", e)

    # -- test plumbing -------------------------------------------------
    def reset(self):
        """Unwind for tests: cancel the deadline, restore the previous
        signal disposition, clear all state."""
        with self._lock:
            t, self._deadline = self._deadline, None
            signum = self._installed_signum
            prev = self._prev_handler
            self._installed_signum = None
            self._prev_handler = None
            self._requested = threading.Event()
            self._reason = ""
            self._t0 = None
            self._managed = False
            self._peer_mono = None
            self._exit = os._exit
        if t is not None:
            t.cancel()
        if signum is not None:
            try:
                _signal.signal(
                    signum, prev if prev is not None else _signal.SIG_DFL)
            except (ValueError, OSError):
                pass


def _kv_from_env():
    addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR)
    port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
    if addr and port:
        from ..backend.rendezvous import RendezvousClient

        return RendezvousClient(addr, port)
    return None


# The process-wide singleton (fault_injection.injector pattern).
coordinator = DrainCoordinator()


def fleet_draining() -> bool:
    return coordinator.fleet_draining()


def commit_barrier(state) -> None:
    """Called once per ``state.commit()`` (after the snapshot + goodput
    bookkeeping, before the host-update check). An allreduce of a
    one-bit drain flag means EVERY rank learns of a pending drain at
    the same commit: all ranks then force this commit durable together
    and the draining rank departs via ``coordinator.execute``. No-op —
    zero collectives, one attribute read — outside managed (elastic
    run loop) mode."""
    coord = coordinator
    if not coord.active():
        return
    from . import basics

    if (not basics.is_initialized() or basics.size() == 1
            or basics.mode() == "mesh"):
        if coord.pending():
            _drain_commit(coord, state, draining=True)
        return
    import numpy as np

    from .. import ops
    from .types import ReduceOp

    flag = np.array([1.0 if coord.pending() else 0.0], np.float32)
    out = ops.allreduce(flag, op=ReduceOp.SUM, name="hvd.drain_pending")
    if float(np.asarray(out)[0]) <= 0.0:
        return
    _drain_commit(coord, state, draining=coord.pending())


def _drain_commit(coord: DrainCoordinator, state, draining: bool):
    events_mod.emit(events_mod.DRAIN_COMMIT, severity=events_mod.WARN,
                    draining=draining, reason=coord.reason)
    mgr = getattr(state, "_checkpoint_manager", None)
    if mgr is not None:
        try:
            mgr.save_now(state, timeout=coord.checkpoint_budget())
        except Exception as e:
            # The drain must still complete: losing the final partial
            # interval is the unannounced-failure bound, not a reason
            # to die mid-protocol.
            logger.error("drain checkpoint failed: %s", e)
    if draining:
        coord.execute(state)
    coord.note_peer_draining()
