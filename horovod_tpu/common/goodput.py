"""Goodput plane: step-level efficiency accounting with badput
attribution (docs/goodput.md).

The observability stack can say *what* is slow (PR 2 counters, PR 6
spans, PR 10 time-series/alerts) but not *how much training it cost*:
nothing answers "what fraction of wall-clock since job start became
useful steps, and which subsystem ate the rest". This module is that
ledger — the standard goodput/badput accounting used to operate large
training fleets, fed by four sources:

* **Step demarcation** — an ``hvd.step()`` scope (or automatic
  boundaries from ``optim/distributed.py``'s update path and
  ``elastic/state.py`` commits) marks the edges of productive steps.
  Each completed step emits a ``step`` span into the PR 6 flight
  recorder with its exposed-comm share in the args.

* **Exposed communication** — ``HandleManager.wait`` reports only the
  time the calling (training) thread actually BLOCKED on a collective:
  a handle that completed while compute overlapped it costs ~0 here,
  so overlapped communication never counts as badput.

* **Checkpoint stall** — the durability plane reports the
  training-thread cost of snapshot copies and counts backpressure
  skips (``common/checkpoint.py``).

* **Restart badput** — generation start/stop stamps plus the
  last-committed-step live in a durable ledger stamp (a tiny JSON next
  to the checkpoints, best-effort mirrored to the rendezvous KV), so a
  kill-all restart's downtime AND the steps replayed after restore are
  counted across process lifetimes. Elastic resets bracket their
  disruption window the same way.

Everything left over is compute (goodput); with a declared per-step
flop count (``HOROVOD_STEP_FLOPS``) the ledger also reports achieved
FLOP/s and — against ``HOROVOD_GOODPUT_PEAK_FLOPS`` — MFU.

The per-rank totals ride the existing telemetry piggyback, so rank 0's
``/goodput`` view attributes badput per rank fleet-wide; the series
land in the PR 10 time-series ring (the sampler snapshots the same
registry) and feed the default ``goodput_degraded`` /
``exposed_comm_regression`` alert rules; the failure post-mortem embeds
the ledger next to the flight recorder.

Ledger identity: one ledger per process (it must survive the engine
swap every elastic reset performs), injectable per engine for the
in-process multi-rank test harness — the registry/tracer pattern.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..utils import atomic_file, clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

STAMP_NAME = "goodput.json"
STAMP_FORMAT = 1
KV_SCOPE = "goodput"
KV_KEY = "status"

# Step-boundary sources, ranked: an explicit hvd.step() scope always
# wins; the optimizer update path beats elastic commits (a loop doing
# both would otherwise count every step twice). The first boundary from
# a higher-ranked source takes the counter over; lower-ranked
# boundaries are ignored from then on.
_SOURCE_RANK = {"commit": 1, "optim": 2, "explicit": 3}


class _StepScope:
    """Context manager for one explicit step (``hvd.step()``)."""

    __slots__ = ("_led", "_t0_ns")

    def __init__(self, led: "GoodputLedger"):
        self._led = led

    def __enter__(self):
        self._led._claim_source("explicit")
        self._led._take_exposed_window()  # pre-step waits are not step comm
        self._t0_ns = clock.mono_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # The step body raised (collective failure mid-step): the
            # step never completed, so it must not count — a phantom
            # completed step would inflate the cursor (over-counting
            # replay after the restore) and its partial duration would
            # pollute the mean step time. The exposure window is
            # dropped from step attribution too (the totals keep it).
            self._led._take_exposed_window()
            return False
        self._led._finish_step(self._t0_ns, clock.mono_ns())
        return False


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SCOPE = _NoopScope()


class GoodputLedger:
    """Job-level step/badput accounting for one rank.

    All ``note_*`` entry points are cheap (a float add under a lock)
    and safe to call from any thread; the heavier stamp persistence is
    rate-limited and rank-0-only."""

    def __init__(self, registry=None, tracer=None, rank: int = 0,
                 stamp_path: Optional[str] = None, kv=None,
                 enabled: Optional[bool] = None,
                 step_flops: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 stamp_seconds: Optional[float] = None):
        if registry is None:
            from . import telemetry

            registry = telemetry.default_registry()
        self.registry = registry
        self.tracer = tracer
        self.rank = rank
        self.enabled = (env_cfg.goodput_enabled() if enabled is None
                        else enabled)
        self.step_flops = (env_cfg.step_flops() if step_flops is None
                           else step_flops)
        self.peak_flops = (env_cfg.goodput_peak_flops()
                           if peak_flops is None else peak_flops)
        self.stamp_seconds = (env_cfg.goodput_stamp_seconds()
                              if stamp_seconds is None else stamp_seconds)
        self.stamp_path = stamp_path
        self._kv = kv
        self._lock = threading.Lock()
        # Generation identity: this process lifetime. The durable stamp
        # carries the FIRST generation's start, so wall-clock spans the
        # whole job across restarts.
        self.gen_start_wall = time.time()
        self.gen_start_mono = time.monotonic()
        self.generation = 1
        self.job_start_wall = self.gen_start_wall
        # Cumulative accounting (prior lifetimes folded in at load).
        self.steps = 0              # steps completed this process
        self.prior_steps = 0
        self.step_seconds = 0.0
        self.prior_step_seconds = 0.0
        # Steps whose duration was actually measured: the first
        # boundary after a reset closes a step whose start was never
        # seen — it counts (the committed-step cursor must track
        # commits 1:1) but must not dilute the mean step time the
        # replay estimate uses.
        self.timed_steps = 0
        self.prior_timed_steps = 0
        self.exposed_seconds = 0.0
        self.prior_exposed_seconds = 0.0
        self.stall_seconds = 0.0
        self.prior_stall_seconds = 0.0
        self.step_stall_seconds = 0.0
        self.prior_step_stall_seconds = 0.0
        self.stall_skips = 0
        # Exposed comm attributed to a completed step's window — the
        # share subtracted from goodput. Out-of-step waits (initial
        # broadcast, eval collectives between explicit scopes, sync
        # during a re-mesh) still count in the exposed TOTAL but live
        # in other/downtime wall time, so subtracting them from step
        # compute would double-count the loss.
        self.step_exposed_seconds = 0.0
        self.prior_step_exposed_seconds = 0.0
        self.downtime_seconds = 0.0     # restart + elastic-reset badput
        self.prior_downtime_seconds = 0.0
        # Announced-preemption badput (docs/fault_tolerance.md): drain
        # windows and their re-mesh, kept apart from `downtime_seconds`
        # (the *failure* bucket) — a fleet losing time to spot reclaims
        # needs different remediation than one losing time to crashes.
        self.preempt_seconds = 0.0
        self.prior_preempt_seconds = 0.0
        self.replayed_steps = 0
        self.prior_replayed_steps = 0
        self.replay_seconds = 0.0
        self.prior_replay_seconds = 0.0
        # Global step cursor: continues from the prior lifetime's stamp
        # so replay after a kill-all restore is computable.
        self.current_step = 0
        self.committed_step = 0
        # Auto-boundary state.
        self._source_rank = 0
        self._boundary_ns: Optional[int] = None
        # Exposed-comm accumulated since the last step edge (the
        # per-step attribution window). A single shared window — waits
        # land on the training thread in every supported loop shape.
        self._window_exposed = 0.0
        self._window_stall = 0.0
        # Disruption bracket (elastic reset / restore in progress).
        self._disrupt_t0: Optional[float] = None
        self._disrupt_reason = ""
        self._disrupt_bucket = "failure"
        self._last_stamp_mono = 0.0
        # Stamp ownership is fixed at construction: only the ORIGINAL
        # rank 0 (the one that loaded the stamp) may write it. A
        # survivor promoted to rank 0 by elastic renumbering never
        # loaded the job history, so letting it write would overwrite
        # the job-lifetime ledger with fresh-lifetime numbers.
        self._stamp_owner = (rank == 0)
        # KV mirror rides a lazy daemon worker (latest-doc-wins): a
        # down rendezvous server must stall the mirror, never the
        # training thread the stamp is written from.
        self._kv_doc: Optional[dict] = None
        self._kv_cond = threading.Condition()
        self._kv_thread: Optional[threading.Thread] = None
        # -- telemetry (docs/metrics.md "Goodput plane") ---------------
        self._m_steps = registry.counter(
            "horovod_goodput_steps_total",
            "Training steps demarcated by the goodput ledger")
        self._m_step_s = registry.histogram(
            "horovod_goodput_step_seconds",
            "Wall duration of demarcated training steps")
        self._m_exposed_step_s = registry.histogram(
            "horovod_exposed_comm_step_seconds",
            "Exposed (training-thread-blocking) communication per step")
        self._m_exposed = registry.counter(
            "horovod_exposed_comm_seconds_total",
            "Seconds the training thread blocked on collective handles "
            "(overlapped communication never counts)")
        self._m_stall = registry.counter(
            "horovod_ckpt_stall_seconds_total",
            "Training-thread seconds lost to checkpoint snapshot copies")
        self._m_downtime = registry.counter(
            "horovod_restart_downtime_seconds_total",
            "Seconds of job downtime: kill-all restart gaps plus "
            "elastic reset/restore windows")
        self._m_preempt = registry.counter(
            "horovod_preemption_badput_seconds_total",
            "Seconds of job downtime attributed to announced "
            "preemptions (graceful drains + their re-mesh), kept apart "
            "from the failure bucket")
        self._m_replayed = registry.counter(
            "horovod_replayed_steps_total",
            "Steps re-executed after a restore (work done twice)")
        self._m_replay_s = registry.counter(
            "horovod_replay_seconds_total",
            "Estimated wall seconds of replayed steps (steps x mean "
            "step time)")
        self._m_generation = registry.gauge(
            "horovod_goodput_generation",
            "Process lifetimes of this job recorded by the ledger")
        self._m_generation.set(1)
        self._m_ratio = registry.gauge(
            "horovod_goodput_ratio",
            "Fraction of job wall-clock spent in productive step "
            "compute (NaN before the first completed step)")
        self._m_ratio.set_function(self._ratio_or_nan)
        if (self.enabled and self.rank == 0
                and (self.stamp_path or self._kv is not None)):
            self._load_stamp()

    # -- durable stamps (rank 0) ---------------------------------------
    def _read_stamp_doc(self) -> Optional[dict]:
        """The newest available stamp: the file on shared storage, or
        — when the file is gone but the rendezvous KV survived (an
        elastic-only restart whose stamp dir was lost) — the KV
        mirror. The mirror is the read fallback, not just a dashboard
        row."""
        if self.stamp_path:
            try:
                with open(self.stamp_path) as f:
                    return json.load(f)
            except (OSError, ValueError):
                pass
        if self._kv is not None:
            try:
                raw = self._kv.get(KV_SCOPE, KV_KEY)
                if raw:
                    return json.loads(raw.decode())
            except Exception:
                pass
        return None

    def _load_stamp(self):
        doc = self._read_stamp_doc()
        if doc is None or doc.get("format") != STAMP_FORMAT:
            return
        now = time.time()
        self.job_start_wall = float(doc.get("job_start_wall",
                                            self.gen_start_wall))
        self.generation = int(doc.get("generation", 0)) + 1
        self._m_generation.set(self.generation)
        # The gap since the previous lifetime's last stamp is restart
        # downtime: the job existed (its ledger says so) but made no
        # progress. Granularity = the stamp cadence. A stamp released
        # by a graceful drain (`draining`) means the previous lifetime
        # ended by ANNOUNCED preemption, so its gap belongs in the
        # preemption bucket, not the failure bucket.
        gap = max(now - float(doc.get("stamp_wall", now)), 0.0)
        if doc.get("draining"):
            self.preempt_seconds += gap
            self._m_preempt.inc(gap)
        else:
            self.downtime_seconds += gap
            self._m_downtime.inc(gap)
        self.prior_steps = int(doc.get("steps", 0))
        self.prior_step_seconds = float(doc.get("step_seconds", 0.0))
        self.prior_timed_steps = int(doc.get("timed_steps", 0))
        self.prior_exposed_seconds = float(doc.get("exposed_seconds", 0.0))
        self.prior_step_exposed_seconds = float(
            doc.get("step_exposed_seconds", 0.0))
        self.prior_stall_seconds = float(doc.get("stall_seconds", 0.0))
        self.prior_step_stall_seconds = float(
            doc.get("step_stall_seconds", 0.0))
        self.prior_downtime_seconds = float(doc.get("downtime_seconds", 0.0))
        self.prior_preempt_seconds = float(doc.get("preempt_seconds", 0.0))
        self.prior_replayed_steps = int(doc.get("replayed_steps", 0))
        self.prior_replay_seconds = float(doc.get("replay_seconds", 0.0))
        self.current_step = int(doc.get("current_step", 0))
        self.committed_step = int(doc.get("committed_step", 0))
        # Carry the demarcation source: replay accounting after a
        # durable restore must know whether the step cursor counts
        # COMMITS (then a manifest step is comparable) or finer-grained
        # optimizer/explicit steps (then it is not — see note_restore).
        self._source_rank = int(doc.get("source_rank", 0))
        logger.info(
            "goodput ledger resumed: generation %d, %.1fs restart "
            "downtime since the previous stamp, step cursor %d "
            "(committed %d)", self.generation, gap, self.current_step,
            self.committed_step)

    def _stamp_doc(self) -> dict:
        return {
            "format": STAMP_FORMAT,
            "job_start_wall": self.job_start_wall,
            "generation": self.generation,
            "stamp_wall": time.time(),
            "steps": self.prior_steps + self.steps,
            "step_seconds": self.prior_step_seconds + self.step_seconds,
            "timed_steps": self.prior_timed_steps + self.timed_steps,
            "exposed_seconds": (self.prior_exposed_seconds
                                + self.exposed_seconds),
            "step_exposed_seconds": (self.prior_step_exposed_seconds
                                     + self.step_exposed_seconds),
            "stall_seconds": self.prior_stall_seconds + self.stall_seconds,
            "step_stall_seconds": (self.prior_step_stall_seconds
                                   + self.step_stall_seconds),
            "downtime_seconds": (self.prior_downtime_seconds
                                 + self.downtime_seconds),
            "preempt_seconds": (self.prior_preempt_seconds
                                + self.preempt_seconds),
            "replayed_steps": self.prior_replayed_steps + self.replayed_steps,
            "replay_seconds": (self.prior_replay_seconds
                               + self.replay_seconds),
            "current_step": self.current_step,
            "committed_step": self.committed_step,
            "source_rank": self._source_rank,
        }

    def release_stamp(self) -> bool:
        """Graceful-drain handoff, owner side (docs/goodput.md "Stamp
        handoff"): force one final stamp marked ``draining`` — written
        synchronously to both the file and the KV mirror, because this
        process is about to exit and the lazy mirror worker may never
        get another turn. The mark does two jobs: a follow-up lifetime
        attributes its restart gap to the *preemption* bucket, and a
        survivor promoted to rank 0 may adopt stamp ownership
        (``try_adopt_stamp``) instead of durable accounting dying with
        the drained process."""
        if not self.enabled or self.rank != 0 or not self._stamp_owner:
            return False
        self._last_stamp_mono = time.monotonic()
        doc = self._stamp_doc()
        doc["draining"] = True
        if self.stamp_path:
            try:
                os.makedirs(os.path.dirname(self.stamp_path) or ".",
                            exist_ok=True)
                atomic_file.atomic_write_text(
                    self.stamp_path, json.dumps(doc), fsync=False)
            except OSError as e:
                logger.warning("goodput release stamp write failed: %s", e)
        if self._kv is not None:
            try:
                self._kv.put(KV_SCOPE, KV_KEY,
                             json.dumps(doc, separators=(",", ":")).encode())
            except Exception:
                pass
        logger.info("goodput: stamp released at step %d (draining)",
                    self.committed_step)
        return True

    def try_adopt_stamp(self) -> bool:
        """Graceful-drain handoff, survivor side: a worker promoted to
        rank 0 by elastic renumbering adopts the durable ledger IF the
        previous owner released it (a ``draining`` stamp). The released
        totals become this ledger's prior lifetimes and its OWN window
        is dropped — the released stamp already accounts the same job
        wall-clock from the rank-0 view, so keeping both would double-
        count. Without a released stamp this is a no-op: an unreleased
        stamp means the owner may still be alive, and overwriting the
        job ledger with fresh-lifetime numbers is exactly what
        construction-time ownership exists to prevent."""
        if not self.enabled or self._stamp_owner:
            return False
        if self.stamp_path is None:
            self.stamp_path = _default_stamp_path()
        if self._kv is None:
            self._kv = _kv_from_env()
        doc = self._read_stamp_doc()
        if (doc is None or doc.get("format") != STAMP_FORMAT
                or not doc.get("draining")):
            return False
        with self._lock:
            self.job_start_wall = float(doc.get("job_start_wall",
                                                self.job_start_wall))
            self.generation = int(doc.get("generation", 0)) + 1
            # Fold the released stamp in as the prior lifetimes and
            # zero this ledger's own window (see docstring).
            self.prior_steps = int(doc.get("steps", 0))
            self.steps = 0
            self.prior_step_seconds = float(doc.get("step_seconds", 0.0))
            self.step_seconds = 0.0
            self.prior_timed_steps = int(doc.get("timed_steps", 0))
            self.timed_steps = 0
            self.prior_exposed_seconds = float(
                doc.get("exposed_seconds", 0.0))
            self.exposed_seconds = 0.0
            self.prior_step_exposed_seconds = float(
                doc.get("step_exposed_seconds", 0.0))
            self.step_exposed_seconds = 0.0
            self.prior_stall_seconds = float(doc.get("stall_seconds", 0.0))
            self.stall_seconds = 0.0
            self.prior_step_stall_seconds = float(
                doc.get("step_stall_seconds", 0.0))
            self.step_stall_seconds = 0.0
            self.prior_downtime_seconds = float(
                doc.get("downtime_seconds", 0.0))
            self.downtime_seconds = 0.0
            self.prior_preempt_seconds = float(
                doc.get("preempt_seconds", 0.0))
            self.preempt_seconds = 0.0
            self.prior_replayed_steps = int(doc.get("replayed_steps", 0))
            self.replayed_steps = 0
            self.prior_replay_seconds = float(doc.get("replay_seconds", 0.0))
            self.replay_seconds = 0.0
            # Steps are collective, so the released cursor and this
            # rank's own agree up to the commit racing the drain; the
            # max is right either way.
            self.current_step = max(self.current_step,
                                    int(doc.get("current_step", 0)))
            self.committed_step = max(self.committed_step,
                                      int(doc.get("committed_step", 0)))
            self._source_rank = max(self._source_rank,
                                    int(doc.get("source_rank", 0)))
            self.rank = 0
            self._stamp_owner = True
        self._m_generation.set(self.generation)
        logger.info(
            "goodput: adopted the released ledger stamp (generation %d, "
            "step cursor %d); durable stamping continues in this process",
            self.generation, self.current_step)
        # Claim immediately: the next reader sees an un-released stamp
        # owned by this lifetime.
        self.stamp(force=True)
        return True

    def stamp(self, force: bool = False):
        """Persist the ledger stamp (the ORIGINAL rank 0 only,
        rate-limited by ``HOROVOD_GOODPUT_STAMP_SECONDS``; 0 = every
        commit). Never fsynced — a crash loses at most one stamp
        interval of downtime resolution, and the commit path must stay
        cheap. The KV mirror is handed to a background worker: a down
        rendezvous server (with its connect retries) must never stall
        the training thread."""
        if not self.enabled or self.rank != 0 or not self._stamp_owner:
            return
        now = time.monotonic()
        if not force and now - self._last_stamp_mono < self.stamp_seconds:
            return
        self._last_stamp_mono = now
        doc = self._stamp_doc()
        if self.stamp_path:
            try:
                os.makedirs(os.path.dirname(self.stamp_path) or ".",
                            exist_ok=True)
                atomic_file.atomic_write_text(
                    self.stamp_path, json.dumps(doc), fsync=False)
            except OSError as e:
                logger.warning("goodput stamp write failed: %s", e)
        if self._kv is not None:
            with self._kv_cond:
                self._kv_doc = doc  # latest wins; a backlog is pointless
                if self._kv_thread is None or not self._kv_thread.is_alive():
                    self._kv_thread = threading.Thread(
                        target=self._kv_loop, name="hvd-goodput-kv",
                        daemon=True)
                    self._kv_thread.start()
                self._kv_cond.notify_all()

    def _kv_loop(self):
        while True:
            with self._kv_cond:
                while self._kv_doc is None:
                    self._kv_cond.wait()
                doc = self._kv_doc
                self._kv_doc = None
            try:
                self._kv.put(KV_SCOPE, KV_KEY,
                             json.dumps(doc, separators=(",", ":")).encode())
            except Exception:  # KV down stalls only this worker
                pass

    # -- step demarcation ----------------------------------------------
    def step(self):
        """Explicit step scope: ``with hvd.step(): train_step(...)``."""
        if not self.enabled:
            return _NOOP_SCOPE
        return _StepScope(self)

    def _claim_source(self, source: str) -> bool:
        """Whether boundaries from `source` currently drive the step
        counter (higher-ranked sources take it over permanently). The
        steady state (same source every step) is a lock-free int
        compare — this sits on the per-step hot path."""
        r = _SOURCE_RANK.get(source, 0)
        cur = self._source_rank
        if r == cur:
            return True
        if r < cur:
            return False
        with self._lock:
            if r > self._source_rank:
                self._source_rank = r
                self._boundary_ns = None  # restart the boundary timer
            return r >= self._source_rank

    def _take_exposed_window(self):
        """Drain the since-last-edge windows: (exposed, stall)."""
        with self._lock:
            w = self._window_exposed
            st = self._window_stall
            self._window_exposed = 0.0
            self._window_stall = 0.0
        return w, st

    def auto_step(self, source: str):
        """Automatic step boundary (optimizer update / state commit):
        the time since the previous boundary from the SAME winning
        source is one step. The first boundary after a disruption (or
        ever) closes a step whose start was never observed — it still
        COUNTS (the step cursor must track commits 1:1 for replay
        accounting) but carries no duration."""
        if not self.enabled or not self._claim_source(source):
            return
        now_ns = clock.mono_ns()
        with self._lock:
            t0 = self._boundary_ns
            self._boundary_ns = now_ns
        self._finish_step(t0, now_ns)

    def _finish_step(self, t0_ns: Optional[int], t1_ns: int):
        timed = t0_ns is not None
        dur = max(t1_ns - t0_ns, 0) / 1e9 if timed else 0.0
        with self._lock:
            exposed = self._window_exposed
            stall = self._window_stall
            self._window_exposed = 0.0
            self._window_stall = 0.0
            self.steps += 1
            self.current_step += 1
            n = self.current_step
            if timed:
                # In-step exposure: what goodput subtraction uses,
                # clamped to the step's own wall time (cross-thread
                # waits must not over-subtract). Untimed boundary
                # steps contribute 0 step_seconds, so their window —
                # which may hold pre-training waits like the initial
                # broadcast — stays out of the subtraction too.
                self.step_exposed_seconds += min(exposed, dur)
                self.step_stall_seconds += min(stall, dur)
                self.timed_steps += 1
                self.step_seconds += dur
        self._m_steps.inc()
        if timed:
            self._m_step_s.observe(dur)
            self._m_exposed_step_s.observe(min(exposed, dur))
            tracer = self.tracer
            if tracer is not None and getattr(tracer, "enabled", False):
                tracer.emit("step", "step", t0_ns, t1_ns - t0_ns,
                            args={"step": n,
                                  "exposed_comm_ms": round(exposed * 1e3,
                                                           3)})

    # -- badput sources ------------------------------------------------
    def note_exposed(self, seconds: float):
        """A collective handle wait actually blocked the caller."""
        if not self.enabled or seconds <= 0:
            return
        self._m_exposed.inc(seconds)
        with self._lock:
            self.exposed_seconds += seconds
            self._window_exposed += seconds

    def note_ckpt_stall(self, seconds: float):
        """Training-thread seconds the durability plane consumed
        (snapshot host copies; the background write itself overlaps)."""
        if not self.enabled or seconds <= 0:
            return
        self._m_stall.inc(seconds)
        with self._lock:
            self.stall_seconds += seconds
            self._window_stall += seconds

    def note_ckpt_skip(self):
        if not self.enabled:
            return
        with self._lock:
            self.stall_skips += 1

    def note_commit(self):
        """A ``state.commit()`` landed: a step boundary (lowest-ranked
        source), the committed-step cursor, and a (rate-limited) stamp."""
        if not self.enabled:
            return
        self.auto_step("commit")
        with self._lock:
            self.committed_step = self.current_step
        self.stamp()

    def note_restore(self, restored_step: Optional[int] = None):
        """The state rolled back (in-memory elastic restore, or a
        durable restore after a kill-all). Steps between the restore
        point and the step cursor were lost and will be re-executed:
        counted ONCE (the cursor rewinds to the restore point, so a
        second restore counts only newly re-run steps) and never
        negative (restoring 'forward' counts nothing).

        `restored_step` is a checkpoint-manifest step, which counts
        elastic COMMITS. It is only comparable to the ledger's cursor
        when commits are the demarcation source; under optimizer or
        explicit demarcation (finer-grained cursors) the manifest
        number would manufacture phantom replay, so the ledger falls
        back to its own committed-step cursor — a sound lower bound in
        its own units."""
        if not self.enabled:
            return
        with self._lock:
            if (restored_step is not None
                    and self._source_rank > _SOURCE_RANK["commit"]):
                restored_step = None
            target = (self.committed_step if restored_step is None
                      else int(restored_step))
            lost = max(self.current_step - target, 0)
            self.current_step = min(self.current_step, target)
            self.committed_step = min(self.committed_step,
                                      self.current_step)
            self.replayed_steps += lost
            mean = self._mean_step_locked()
            replay_s = lost * mean
            self.replay_seconds += replay_s
            # A rollback also invalidates the running boundary/window:
            # the next step starts fresh.
            self._boundary_ns = None
            self._window_exposed = 0.0
            self._window_stall = 0.0
        if lost:
            self._m_replayed.inc(lost)
            self._m_replay_s.inc(replay_s)
            from . import events as events_mod

            events_mod.emit(events_mod.CKPT_REPLAY,
                            severity=events_mod.WARN, rank=self.rank,
                            restored_step=target, lost_steps=lost,
                            replay_seconds=round(replay_s, 3))
            logger.info(
                "goodput: restore to step %d loses %d executed steps "
                "(~%.1fs of replay badput)", target, lost, replay_s)

    def disruption_begin(self, reason: str = "", bucket: str = "failure"):
        """A failure/reset window opened: wall time until
        ``disruption_end`` is restart-badput, and step boundaries are
        suspended so the gap never reads as one giant step. `bucket`
        picks the attribution: ``"failure"`` (the default — crashes,
        liveness evictions, unannounced loss) or ``"preemption"``
        (announced drains; docs/fault_tolerance.md). An already-open
        window keeps its original reason but may be UPGRADED to the
        preemption bucket: the drain notice often arrives after the
        collective failure it caused was already bracketed."""
        if not self.enabled:
            return
        if bucket not in ("failure", "preemption"):
            bucket = "failure"
        with self._lock:
            if self._disrupt_t0 is None:
                self._disrupt_t0 = time.monotonic()
                self._disrupt_reason = reason
                self._disrupt_bucket = bucket
            elif bucket == "preemption":
                self._disrupt_bucket = bucket
            self._boundary_ns = None
        tracer = self.tracer
        if tracer is not None and getattr(tracer, "enabled", False):
            tracer.instant("goodput.disruption", cat="goodput",
                           args={"reason": reason, "bucket": bucket})

    def disruption_end(self):
        """Training is live again; the window closes into the
        restart-downtime bucket (or the preemption bucket for an
        announced drain). No-op without an open window."""
        if not self.enabled:
            return
        with self._lock:
            t0 = self._disrupt_t0
            self._disrupt_t0 = None
            reason = self._disrupt_reason
            self._disrupt_reason = ""
            bucket = self._disrupt_bucket
            self._disrupt_bucket = "failure"
            if t0 is None:
                return
            dt = max(time.monotonic() - t0, 0.0)
            if bucket == "preemption":
                self.preempt_seconds += dt
            else:
                self.downtime_seconds += dt
        if bucket == "preemption":
            self._m_preempt.inc(dt)
        else:
            self._m_downtime.inc(dt)
        logger.info("goodput: %.2fs of %s downtime (%s)", dt, bucket,
                    reason or "disruption")
        self.stamp()

    # -- derived math ---------------------------------------------------
    def _mean_step_locked(self) -> float:
        n = self.prior_timed_steps + self.timed_steps
        s = self.prior_step_seconds + self.step_seconds
        return s / n if n > 0 else 0.0

    def wall_seconds(self, now_wall: Optional[float] = None) -> float:
        now = time.time() if now_wall is None else now_wall
        return max(now - self.job_start_wall, 0.0)

    def _totals(self) -> dict:
        with self._lock:
            return {
                "steps": self.prior_steps + self.steps,
                "step_seconds": self.prior_step_seconds + self.step_seconds,
                "exposed_seconds": (self.prior_exposed_seconds
                                    + self.exposed_seconds),
                "step_exposed_seconds": (self.prior_step_exposed_seconds
                                         + self.step_exposed_seconds),
                "stall_seconds": (self.prior_stall_seconds
                                  + self.stall_seconds),
                "step_stall_seconds": (self.prior_step_stall_seconds
                                       + self.step_stall_seconds),
                "stall_skips": self.stall_skips,
                "downtime_seconds": (self.prior_downtime_seconds
                                     + self.downtime_seconds),
                "preempt_seconds": (self.prior_preempt_seconds
                                    + self.preempt_seconds),
                "replayed_steps": (self.prior_replayed_steps
                                   + self.replayed_steps),
                "replay_seconds": (self.prior_replay_seconds
                                   + self.replay_seconds),
                "current_step": self.current_step,
                "committed_step": self.committed_step,
                "mean_step_seconds": self._mean_step_locked(),
            }

    def goodput_seconds(self, totals: Optional[dict] = None) -> float:
        """Productive compute: step wall time minus the badput that
        happened INSIDE steps — the in-step exposed share (out-of-step
        waits live in other/downtime wall time and subtracting them
        here would double-count), snapshot stalls, and replayed work.
        Clamped at 0 — accounting noise must never go negative."""
        t = totals or self._totals()
        return max(t["step_seconds"] - t["step_exposed_seconds"]
                   - t["step_stall_seconds"] - t["replay_seconds"], 0.0)

    def _ratio_from(self, t: dict, wall: float) -> Optional[float]:
        if t["steps"] <= 0 or wall <= 0:
            return None
        return min(self.goodput_seconds(t) / wall, 1.0)

    def ratio(self, now_wall: Optional[float] = None) -> Optional[float]:
        """goodput_seconds / job wall-clock, in [0, 1]; None before the
        first completed step (no ratio is better than a made-up one)."""
        return self._ratio_from(self._totals(), self.wall_seconds(now_wall))

    def _ratio_or_nan(self) -> float:
        r = self.ratio()
        return float("nan") if r is None else r

    def view(self) -> dict:
        """The full ledger document: the /goodput body's ``local``
        section, the /status ``goodput`` section, and the post-mortem
        embed."""
        t = self._totals()
        wall = self.wall_seconds()
        good = self.goodput_seconds(t)
        badput = {
            "exposed_comm_seconds": round(t["exposed_seconds"], 4),
            "exposed_comm_in_step_seconds": round(
                t["step_exposed_seconds"], 4),
            "ckpt_stall_seconds": round(t["stall_seconds"], 4),
            "ckpt_stall_in_step_seconds": round(
                t["step_stall_seconds"], 4),
            "ckpt_backpressure_skips": t["stall_skips"],
            "restart_downtime_seconds": round(t["downtime_seconds"], 4),
            "preemption_seconds": round(t["preempt_seconds"], 4),
            "replayed_steps": t["replayed_steps"],
            "replay_seconds": round(t["replay_seconds"], 4),
            # Wall time outside steps and outside disruptions: init,
            # input pipeline, evaluation — unattributed overhead.
            "other_seconds": round(
                max(wall - t["step_seconds"] - t["downtime_seconds"]
                    - t["preempt_seconds"], 0.0),
                4),
        }
        out = {
            "enabled": self.enabled,
            "generation": self.generation,
            "job_start_wall": self.job_start_wall,
            "wall_seconds": round(wall, 4),
            "steps": {
                "total": t["steps"],
                "this_process": self.steps,
                "current_step": t["current_step"],
                "committed_step": t["committed_step"],
                "mean_step_seconds": round(t["mean_step_seconds"], 6),
            },
            "goodput": {
                "seconds": round(good, 4),
                # From the same totals/wall as the other fields, so the
                # document is internally consistent under concurrency.
                "ratio": self._ratio_from(t, wall),
            },
            "badput": badput,
        }
        if self.step_flops > 0 and t["mean_step_seconds"] > 0:
            flops_s = self.step_flops / t["mean_step_seconds"]
            out["flops"] = {
                "step_flops": self.step_flops,
                "achieved_flops_per_second": flops_s,
            }
            if self.peak_flops > 0:
                out["flops"]["mfu"] = round(flops_s / self.peak_flops, 4)
        return out

    def status_summary(self) -> dict:
        """Compact form for the /status ``goodput`` section."""
        v = self.view()
        return {
            "enabled": v["enabled"],
            "generation": v["generation"],
            "steps": v["steps"]["total"],
            "goodput_ratio": v["goodput"]["ratio"],
            "exposed_comm_seconds": v["badput"]["exposed_comm_seconds"],
            "restart_downtime_seconds":
                v["badput"]["restart_downtime_seconds"],
            "preemption_seconds": v["badput"]["preemption_seconds"],
            "replayed_steps": v["badput"]["replayed_steps"],
        }


# ---------------------------------------------------------------------------
# Process-wide ledger (survives elastic engine swaps). The in-process
# multi-rank test harness constructs private ledgers instead.

_current: Optional[GoodputLedger] = None
_current_lock = threading.Lock()


def _default_stamp_path() -> Optional[str]:
    d = env_cfg.goodput_dir()
    return os.path.join(d, STAMP_NAME) if d else None


def _kv_from_env():
    addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR)
    port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
    if addr and port:
        from ..backend.rendezvous import RendezvousClient

        return RendezvousClient(addr, port)
    return None


def current(rank: Optional[int] = None) -> GoodputLedger:
    """The process ledger, created on first use. `rank` seeds the
    first creation when the caller knows better than the environment —
    mesh mode has no HOROVOD_RANK (that absence is what selects mesh
    mode), so basics.init passes jax's process index; every process
    defaulting to rank 0 there would make N stamp owners."""
    global _current
    with _current_lock:
        if _current is None:
            if rank is None:
                rank = env_cfg.get_int(env_cfg.RANK, 0)
            _current = GoodputLedger(
                rank=rank,
                stamp_path=_default_stamp_path(),
                kv=_kv_from_env() if rank == 0 else None)
        return _current


def set_current(led: Optional[GoodputLedger]):
    global _current
    with _current_lock:
        _current = led


def active() -> Optional[GoodputLedger]:
    """The process ledger if one exists — the hook form used by the
    checkpoint/elastic planes, which must stay no-ops in processes that
    never initialized goodput accounting."""
    return _current


def for_engine(registry, rank: int, tracer=None) -> GoodputLedger:
    """The ledger an Engine should feed. Engines on the process-default
    registry share the process ledger (it outlives them across elastic
    resets); an engine with a private registry (the in-process
    multi-rank harness) gets a private ledger so per-"rank" accounting
    stays separable."""
    from . import telemetry

    if registry is telemetry.default_registry():
        led = current()
        if rank == 0 and led.rank != 0 and not led._stamp_owner:
            # A survivor promoted to coordinator by elastic
            # renumbering. If the previous owner RELEASED the stamp (a
            # graceful drain), adopt it — ownership hands off and
            # durable accounting continues here. Otherwise it never
            # loaded the job-lifetime stamp, so it must not overwrite
            # it with fresh-lifetime numbers — durable stamping stays
            # with the original rank 0's lifetime (per-lifetime
            # accounting continues locally).
            if not led.try_adopt_stamp():
                logger.info(
                    "goodput: promoted to rank 0 mid-job; durable ledger "
                    "stamping remains disabled in this process")
        led.rank = rank  # elastic renumbering: the live rank wins
    else:
        led = GoodputLedger(registry=registry, rank=rank)
    if tracer is not None:
        led.tracer = tracer
    return led


# -- module-level hook forms (no-ops without a live ledger) -----------------

def step():
    """``hvd.step()``: demarcate one training step explicitly."""
    return current().step()


def auto_step(source: str):
    led = active()
    if led is not None:
        led.auto_step(source)


def note_commit():
    led = active()
    if led is not None:
        led.note_commit()


def note_restore(restored_step: Optional[int] = None):
    led = active()
    if led is not None:
        led.note_restore(restored_step)


def note_ckpt_stall(seconds: float):
    led = active()
    if led is not None:
        led.note_ckpt_stall(seconds)


def note_ckpt_skip():
    led = active()
    if led is not None:
        led.note_ckpt_skip()


def disruption_begin(reason: str = "", bucket: str = "failure"):
    led = active()
    if led is not None:
        led.disruption_begin(reason, bucket=bucket)


def release_stamp():
    """Graceful-drain hook: the draining owner's final ``draining``
    stamp (no-op without a live owning ledger)."""
    led = active()
    if led is not None:
        led.release_stamp()


def disruption_end():
    led = active()
    if led is not None:
        led.disruption_end()
