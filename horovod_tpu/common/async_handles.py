"""Local (size-1) result store shared by the framework adapters'
async-handle APIs (torch, tensorflow). Engine handles are non-negative;
local handles count down from -1 so the two spaces never collide."""
from __future__ import annotations

import numpy as np


class LocalResultStore:
    def __init__(self):
        self._next = 0
        self._results = {}

    def put(self, result) -> int:
        # Snapshot ndarrays: callers may pass views aliasing framework
        # tensor storage, and the engine path returns fresh buffers, so
        # this path must too.
        if isinstance(result, np.ndarray):
            result = np.array(result)
        self._next -= 1
        self._results[self._next] = result
        return self._next

    def pop(self, handle: int):
        return self._results.pop(handle)

    def __contains__(self, handle: int) -> bool:
        return handle in self._results
