"""Public collective-op API: hvd.allreduce / allgather / broadcast /
alltoall / join / barrier (+ async variants).

Dispatch (TPU-first design — ONE API across both data planes, ROADMAP
item 2 / docs/running.md "Traced collectives"):

* **Traced inputs with a resolvable mesh axis** (jax tracers inside
  jit/pjit/shard_map where `resolve_axis` finds a bound named axis):
  lower directly to XLA collectives over that axis (ops/traced.py) —
  the hot path; gradients never leave the device, XLA fuses and
  overlaps the collectives with the backward pass, and zero bytes ride
  the host engine.
* **Traced inputs, no bound axis, mesh mode** (plain jit/pjit over a
  GSPMD mesh): arrays are global, so collectives take their closed
  forms (sum = x·size, gather = tile, bcast = identity) and XLA derives
  the real wire collectives from the array shardings instead.
* **Concrete inputs, process mode**: the asynchronous name-negotiated
  engine (ref: horovod/torch/mpi_ops.py:83-219 handle API).
* **Concrete inputs, mesh mode** (single-controller SPMD): the same
  closed forms — every "rank" of a single-controller program holds the
  same logical value. This keeps unmodified single-process scripts
  correct before they are scaled out — the same property
  `horovodrun -np 1` has in the reference.

The axis-resolution rule (`resolve_axis`) is collectively consistent by
construction: it reads only trace state and process-wide configuration
that the launcher propagates identically to every rank, never per-rank
state — so the same script takes the same dispatch branch on every rank
whether it runs in mesh mode or under `hvdrun`.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..common import basics, telemetry
from ..common.exceptions import HorovodInternalError
from ..common.types import ReduceOp
from . import traced as _traced

# Canonical data-parallel axis names tried (in order) when no explicit
# axis_name= is given and the init axis is not bound: the parallel/
# mesh convention's dp axis, then the default 1-D mesh axis.
_DATA_AXIS_CANDIDATES = ("dp", "hvd")

# Traced dispatches, counted HOST-SIDE AT TRACE TIME (once per traced
# call site per compilation, not once per executed step — XLA owns the
# compiled program's wire, so there is no per-step host hook; see
# docs/metrics.md). Nonzero means the XLA plane was engaged.
_TRACED_OPS_HELP = ("Collective dispatches lowered to XLA collectives "
                    "(counted at trace time, labeled by op)")


def _count_traced(op: str):
    telemetry.counter("horovod_traced_ops_total", _TRACED_OPS_HELP,
                      labels={"op": op}).inc()


def _is_tracer(x) -> bool:
    try:
        import jax.core

        return isinstance(x, jax.core.Tracer)
    except Exception:  # pragma: no cover
        return False


def _bound_axes() -> Tuple[str, ...]:
    """Named axes live in the current trace (inside shard_map/pmap).
    Under plain jit/pjit no axis is bound — there, arrays are global
    and collectives take their closed forms.

    Private-API drift FAILS LOUDLY: silently returning () here would
    make every hvd.allreduce inside a shard_map body fall to the
    mesh-mode closed forms on PER-SHARD values — corrupted gradients,
    no error. A trace-time exception is the correct failure mode."""
    try:
        from jax._src.core import get_axis_env

        return tuple(get_axis_env().axis_sizes)
    except Exception as exc:  # pragma: no cover — private-API drift
        raise HorovodInternalError(
            "jax private-API drift: jax._src.core.get_axis_env is "
            "unavailable, so traced-dispatch axis resolution cannot "
            "see bound mesh axes — update "
            "horovod_tpu/ops/__init__.py:_bound_axes for this jax "
            f"version ({exc!r})"
        ) from exc


def resolve_axis(axis_name=None):
    """The collectively-consistent axis-resolution rule: which named
    mesh axis a traced collective reduces over (docs/running.md
    "Traced collectives").

    1. An explicit ``axis_name=`` argument wins (string, or a tuple of
       axis names for data sharded over several mesh axes).
    2. The init axis (``hvd.init`` mesh axis, default "hvd") when it is
       bound in the current trace.
    3. The canonical DATA axes — "dp", then "hvd" — when bound. On a
       2-D data×model mesh (dp×tp / dp×sp / pp×dp...) this picks the
       data axis ONLY: model-parallel axes (tp/sp/pp/ep) are never
       gradient-reduction axes, so `DistributedOptimizer` composes with
       the parallel/ kernels without configuration.

    Returns None when nothing resolves (plain jit, or eager). Only
    trace state and launcher-propagated config are consulted — never
    per-rank state — so every rank takes the same branch."""
    if axis_name is not None:
        return axis_name
    bound = _bound_axes()
    if not bound:
        return None
    an = basics.axis_name() if basics.is_initialized() else None
    if an is not None and an in bound:
        return an
    for cand in _DATA_AXIS_CANDIDATES:
        if cand in bound:
            return cand
    return None


def _use_traced(x, axis_name: Optional[str]) -> bool:
    return _is_tracer(x) and resolve_axis(axis_name) is not None


def _axis(axis_name: Optional[str]):
    ax = resolve_axis(axis_name)
    if ax is None:
        # Callers dispatch here only after _use_traced confirmed a
        # resolvable axis; failing loudly beats falling back to an
        # axis that is not bound in the current trace.
        raise ValueError(
            "no mesh axis bound; pass axis_name= or call inside "
            "shard_map over the data axis"
        )
    return ax


def _resolve_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    # Back-compat `average=` kwarg (ref: horovod/torch/mpi_ops.py:83-110).
    if op is not None and average is not None:
        raise ValueError("specify either op= or the legacy average=, not both")
    if op is None:
        op = ReduceOp.AVERAGE if (average is None or average) else ReduceOp.SUM
    return op


# ---------------------------------------------------------------------------
# allreduce
def allreduce(
    tensor,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    axis_name: Optional[str] = None,
):
    """All-reduce across ranks (ref: horovod/tensorflow/__init__.py:52-149,
    horovod/torch/mpi_ops.py allreduce)."""
    rop = _resolve_op(op, average)
    if _use_traced(tensor, axis_name):
        _count_traced("allreduce")
        return _traced.allreduce(
            tensor, _axis(axis_name), rop, prescale_factor, postscale_factor
        )
    if _is_tracer(tensor) and basics.mode() == "process":
        raise ValueError(
            "collectives inside jit require a bound mesh axis in process "
            "mode; wrap the step in shard_map (hvd.wrap_step binds the "
            "data axis) or use the eager API"
        )
    if basics.mode() == "process":
        h = allreduce_async(tensor, name=name, op=rop,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor)
        return synchronize(h)
    # mesh mode, concrete
    import jax.numpy as jnp

    n = basics.size()
    x = tensor * prescale_factor if prescale_factor != 1.0 else tensor
    if rop == ReduceOp.SUM:
        out = x * n
    elif rop == ReduceOp.AVERAGE:
        out = x
    elif rop in (ReduceOp.MIN, ReduceOp.MAX):
        out = x
    elif rop == ReduceOp.PRODUCT:
        out = x**n
    elif rop == ReduceOp.ADASUM:
        # n identical vectors adasum-combine to x (pairwise combine of
        # (v, v) gives v: coefficients (1 - 1/2) + (1 - 1/2) = 1).
        out = x
    else:
        raise ValueError(f"unsupported op {rop}")
    return out * postscale_factor if postscale_factor != 1.0 else out


def allreduce_async(
    tensor,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> int:
    """(ref: horovod/torch/mpi_ops.py:117-161)"""
    rop = _resolve_op(op, average)
    eng = basics.engine()
    if eng is None:
        raise HorovodInternalError("async API requires process mode (hvdrun)")
    return eng.enqueue_allreduce(
        np.asarray(tensor), name=name, op=rop,
        prescale=prescale_factor, postscale=postscale_factor,
    )


def grouped_allreduce(
    tensors: Sequence,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    axis_name: Optional[str] = None,
):
    rop = _resolve_op(op, average)
    if tensors and _use_traced(tensors[0], axis_name):
        _count_traced("grouped_allreduce")
        return _traced.grouped_allreduce(
            tensors, _axis(axis_name), rop, prescale_factor, postscale_factor
        )
    if basics.mode() == "process":
        base = name or "grouped"
        handles = [
            allreduce_async(t, name=f"{base}.{i}", op=rop,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor)
            for i, t in enumerate(tensors)
        ]
        return [synchronize(h) for h in handles]
    return [
        allreduce(t, op=rop, prescale_factor=prescale_factor,
                  postscale_factor=postscale_factor)
        for t in tensors
    ]


# ---------------------------------------------------------------------------
# allgather
def allgather(tensor, name: Optional[str] = None, axis_name: Optional[str] = None):
    """Concatenate ranks' tensors along dim 0; first dims may differ in
    eager mode (ref: collective_operations.h:148-185)."""
    if _use_traced(tensor, axis_name):
        _count_traced("allgather")
        return _traced.allgather(tensor, _axis(axis_name))
    if basics.mode() == "process":
        return synchronize(allgather_async(tensor, name=name))
    import jax.numpy as jnp

    x = jnp.asarray(tensor)
    reps = (basics.size(),) + (1,) * (x.ndim - 1) if x.ndim else (basics.size(),)
    return jnp.tile(x if x.ndim else x[None], reps)


def allgather_async(tensor, name: Optional[str] = None) -> int:
    eng = basics.engine()
    if eng is None:
        raise HorovodInternalError("async API requires process mode (hvdrun)")
    return eng.enqueue_allgather(np.asarray(tensor), name=name)


# ---------------------------------------------------------------------------
# broadcast
def broadcast(
    tensor, root_rank: int = 0, name: Optional[str] = None,
    axis_name: Optional[str] = None,
):
    """(ref: horovod/torch/mpi_ops.py broadcast)"""
    if _use_traced(tensor, axis_name):
        _count_traced("broadcast")
        return _traced.broadcast(tensor, root_rank, _axis(axis_name))
    if basics.mode() == "process":
        return synchronize(broadcast_async(tensor, root_rank, name=name))
    return tensor


def broadcast_async(tensor, root_rank: int = 0, name: Optional[str] = None) -> int:
    eng = basics.engine()
    if eng is None:
        raise HorovodInternalError("async API requires process mode (hvdrun)")
    return eng.enqueue_broadcast(np.asarray(tensor), root_rank, name=name)


# ---------------------------------------------------------------------------
# alltoall
def alltoall(
    tensor, splits: Optional[Sequence[int]] = None, name: Optional[str] = None,
    axis_name: Optional[str] = None,
):
    """(ref: operations.cc:979-1042; uneven splits eager-only — dynamic
    shapes don't jit). Returns (output, recv_splits) in eager mode to
    match hvd.alltoall's splits return."""
    if _use_traced(tensor, axis_name):
        if splits is not None:
            raise ValueError("uneven alltoall splits are eager-only on TPU")
        _count_traced("alltoall")
        return _traced.alltoall(tensor, _axis(axis_name))
    if basics.mode() == "process":
        return synchronize(alltoall_async(tensor, splits, name=name))
    import jax.numpy as jnp

    x = jnp.asarray(tensor)
    return x, [int(s) for s in (splits if splits is not None
                                else [x.shape[0] // basics.size()] * basics.size())]


def alltoall_async(tensor, splits=None, name: Optional[str] = None) -> int:
    eng = basics.engine()
    if eng is None:
        raise HorovodInternalError("async API requires process mode (hvdrun)")
    return eng.enqueue_alltoall(
        np.asarray(tensor), list(splits) if splits is not None else None, name=name
    )


# ---------------------------------------------------------------------------
# reducescatter (TPU-native addition; the hierarchical building block)
def reducescatter(tensor, op: Optional[ReduceOp] = None,
                  axis_name: Optional[str] = None,
                  name: Optional[str] = None):
    """Reduce across ranks, leaving each rank its 1/n slice of dim 0 —
    the ZeRO gradient leg (docs/running.md "ZeRO sharded optimizer
    state"). `name` keys the engine's response cache like any
    collective, so steady-state loops skip renegotiation (and the
    `reducescatter_16mb_ms` perf stage measures the cached path)."""
    rop = op or ReduceOp.SUM
    if _use_traced(tensor, axis_name):
        _count_traced("reducescatter")
        return _traced.reducescatter(tensor, _axis(axis_name), rop)
    if basics.mode() == "process":
        # Allreduce then take this rank's slice.
        full = allreduce(tensor, op=rop if rop != ReduceOp.SUM else None,
                         average=None if rop != ReduceOp.SUM else False,
                         name=name)
        n = basics.size()
        r = basics.rank()
        per = full.shape[0] // n
        return full[r * per : (r + 1) * per]
    return tensor


# ---------------------------------------------------------------------------
# join / barrier
def join() -> int:
    """Signal this rank has exhausted its data; it participates in
    subsequent allreduces with zeros until every rank joins
    (ref: operations.cc:1044-1068, controller.cc:220-308). Returns the
    last joined rank."""
    if basics.mode() == "process":
        eng = basics.engine()
        out = eng.synchronize(eng.enqueue_join())
        return int(out) if out is not None else -1
    return basics.size() - 1


def barrier():
    """(ref: horovod barrier op)"""
    if basics.mode() == "process":
        eng = basics.engine()
        eng.synchronize(eng.enqueue_barrier())


# ---------------------------------------------------------------------------
# handle API
def poll(handle: int) -> bool:
    """(ref: horovod/torch/mpi_ops.py:poll)"""
    return basics.engine().poll(handle)


def synchronize(handle: int):
    """(ref: horovod/torch/mpi_ops.py:synchronize)"""
    return basics.engine().synchronize(handle)
