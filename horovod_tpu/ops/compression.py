"""Gradient compression (ref: horovod/torch/compression.py:20-74,
horovod/tensorflow/compression.py:46-64).

The interface (`Compressor`) and the identity compressor live ONCE in
`common/compression.py` — the same module that implements the
data-plane wire codecs (docs/running.md "Wire compression") — so the
three framework namespaces (this one, `tensorflow/compression.py`,
`torch/compression.py`) can never drift: each is a thin re-export plus
its tensor-type adapters. This module carries the JAX adapters.

The reference ships a none-compressor and an fp16 compressor. On TPU
the natural compressed wire type is bfloat16 (same byte savings as
fp16, wider exponent range, native MXU type), so `Compression.fp16`
maps to bf16 by default; `Compression.true_fp16` keeps IEEE fp16 for
parity.

Note the division of labor: these compressors convert the TENSOR the
engine then carries end to end (framework-level, opt-in per
optimizer); the wire codec layer in `common/compression.py` narrows
only the BYTES ON THE WIRE while the engine math stays fp32, with
error feedback — prefer `HOROVOD_WIRE_COMPRESSION` for gradient
traffic.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..common.compression import Compressor, NoneCompressor

__all__ = [
    "Compressor",
    "NoneCompressor",
    "BF16Compressor",
    "FP16Compressor",
    "Compression",
]


class BF16Compressor(Compressor):
    """Compress float tensors to bfloat16 for the wire."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and tensor.dtype != jnp.bfloat16:
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None and tensor.dtype != ctx else tensor


class FP16Compressor(Compressor):
    """(ref: compression.py FP16Compressor)"""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and tensor.dtype != jnp.float16:
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None and tensor.dtype != ctx else tensor


class Compression:
    """(ref: compression.py Compression namespace)"""

    none = NoneCompressor
    fp16 = BF16Compressor  # TPU-native default: bf16 on the wire
    true_fp16 = FP16Compressor
    bf16 = BF16Compressor
