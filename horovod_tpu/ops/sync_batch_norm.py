"""Synchronized batch normalization across ranks.

(ref: horovod/torch/sync_batch_norm.py:1-199 — allreduce of per-rank
mean/var + count; horovod/tensorflow/sync_batch_norm.py:22-65.)

TPU-native: inside jit the cross-chip moment reduction is a single fused
psum over the data axis. `SyncBatchNorm` is a flax module; the
functional `sync_batch_stats` serves hand-rolled models.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common import basics


def _maybe_axis(axis_name: Optional[str]) -> Optional[str]:
    if axis_name is not None:
        return axis_name
    try:
        return basics.axis_name()
    except Exception:
        return None


def sync_batch_stats(x, axis_name: Optional[str] = None, reduce_dims=None):
    """Mean/variance over batch dims AND the mesh axis.

    Matches the reference's algorithm: allreduce of sum and sum-of-squares
    with the global element count (ref: torch/sync_batch_norm.py:93-135).
    """
    an = _maybe_axis(axis_name)
    if reduce_dims is None:
        reduce_dims = tuple(range(x.ndim - 1))  # all but features
    local_sum = jnp.sum(x, axis=reduce_dims)
    local_sq = jnp.sum(jnp.square(x), axis=reduce_dims)
    local_n = 1
    for d in reduce_dims:
        local_n *= x.shape[d]
    n = jnp.asarray(local_n, jnp.float32)
    try:
        is_traced = isinstance(x, jax.core.Tracer)
    except Exception:  # pragma: no cover
        is_traced = False
    if an is not None and is_traced:
        local_sum = lax.psum(local_sum, an)
        local_sq = lax.psum(local_sq, an)
        n = lax.psum(n, an)
    mean = local_sum / n
    var = local_sq / n - jnp.square(mean)
    return mean, var


try:
    import flax.linen as nn

    class SyncBatchNorm(nn.Module):
        """Drop-in BatchNorm whose batch statistics are reduced across the
        data-parallel mesh axis (flax BatchNorm natively supports this via
        axis_name — the TPU-idiomatic form of the reference's handwritten
        allreduce at torch/sync_batch_norm.py:93-135)."""

        use_running_average: bool = False
        momentum: float = 0.9
        epsilon: float = 1e-5
        dtype: Optional[object] = None
        axis_name: Optional[str] = None

        @nn.compact
        def __call__(self, x, use_running_average: Optional[bool] = None):
            an = self.axis_name or _maybe_axis(None)
            return nn.BatchNorm(
                use_running_average=self.use_running_average
                if use_running_average is None
                else use_running_average,
                momentum=self.momentum,
                epsilon=self.epsilon,
                dtype=self.dtype,
                axis_name=an,
            )(x)

except ImportError:  # pragma: no cover
    SyncBatchNorm = None
