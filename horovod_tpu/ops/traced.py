"""Traced collectives: the XLA/ICI data plane.

These run *inside* jit/pjit/shard_map over a named mesh axis and lower
directly to XLA collectives on ICI — the TPU-native replacement for the
reference's NCCL/MPI/Gloo data ops (ref: horovod/common/ops/
nccl_operations.cc:126-187, mpi_operations.cc:26-186,
gloo_operations.cc:119-330).

Design note: the reference needs an asynchronous engine because GPU
frameworks issue ops in nondeterministic order across ranks
(ref: operations.cc:332-351). Under jit the collective sequence is static
and identical on every chip, so XLA can schedule, fuse and overlap them —
the negotiation phase disappears and what remains is exactly these ops.
Tensor fusion (ref: controller.cc:686-809) maps to XLA's collective
combiner plus our grouped_* ops which concatenate flat buffers explicitly.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..common.types import ReduceOp

from ..utils.compat import axis_index as _axis_index, axis_size as _axis_size


def _traced_wire_dtype(x, op: ReduceOp):
    """Traced-path analogue of the eager wire codec (docs/running.md
    "Wire compression" / "Traced collectives"): the dtype gradients are
    cast to before the psum, or None for full-width.

    Mirrors the eager coordinator policy — fp32 SUM/AVERAGE allreduces
    only, ``HOROVOD_WIRE_COMPRESSION=bf16|fp16|auto`` (auto picks
    bf16), with the ``HOROVOD_WIRE_COMPRESSION_MIN_BYTES`` floor on the
    pre-cast payload. Semantics differ from the eager codec in two
    deliberate ways, both documented: the cast is STATELESS (no error
    feedback — carrying the residual across steps needs cross-step
    state, which `DistributedOptimizer(error_feedback=True)` threads
    through as optimizer state; a bare traced `hvd.allreduce` has
    nowhere to keep it), and the psum itself runs in the narrow dtype
    (the eager engine reduces in fp32 at full width and only ships
    narrow). The int8-with-scale lane (`_traced_int8_enabled`) is the
    exception: it gathers quantized contributions and decode-sums in
    fp32, matching the eager "reduce full-width, ship narrow"
    semantics. Knobs are read at TRACE time and baked into the
    compiled step — collectively consistent because the launcher
    propagates the env to every rank, but a mid-run flip needs a
    retrace, unlike the per-call eager knobs."""
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        return None
    from ..utils import env as env_cfg

    mode = env_cfg.wire_compression_mode()
    if mode == "none" or x.dtype != jnp.float32:
        return None
    if x.size * x.dtype.itemsize < env_cfg.wire_compression_min_bytes():
        return None
    dt = jnp.float16 if mode == "fp16" else jnp.bfloat16
    from ..common import telemetry

    telemetry.counter(
        "horovod_traced_compressed_ops_total",
        "Traced allreduces compiled with a pre-psum wire cast "
        "(counted at trace time, labeled by codec)",
        labels={"codec": "fp16" if mode == "fp16" else "bf16"},
    ).inc()
    return dt


def _traced_int8_enabled(x, op: ReduceOp) -> bool:
    """Gate for the traced int8-with-scale wire lane — the same policy
    shape as the eager latency-channel int8 opt-in
    (``HOROVOD_WIRE_COMPRESSION_INT8`` engages only when a non-none
    codec mode is active): fp32 SUM/AVERAGE tensors at or above the
    min-bytes floor, opt-in, and trace-time like every traced knob."""
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        return False
    from ..utils import env as env_cfg

    if not env_cfg.wire_compression_int8():
        return False
    if env_cfg.wire_compression_mode() == "none":
        return False
    if x.dtype != jnp.float32:
        return False
    return x.size * x.dtype.itemsize >= env_cfg.wire_compression_min_bytes()


def int8_encode(x):
    """Per-tensor symmetric int8 quantization: (q, scale) with
    ``x ≈ q · scale``, scale = max|x|/127 (the eager codec's
    int8-with-scale layout, common/compression.py)."""
    scale = jnp.max(jnp.abs(x)) / 127.0
    scale = jnp.maximum(scale, jnp.float32(1e-30))  # all-zero tensors
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def _int8_allreduce(x, axis_name):
    """int8 traced wire lane: each rank ships its quantized tensor plus
    one fp32 scale (all_gather — the int8 payload is what rides the
    wire), then decode-sums locally in fp32. Summing in int8 would
    overflow at 2 ranks; this keeps the eager engine's "reduce at full
    width, ship narrow" contract. Wire cost per rank is ~size bytes vs
    the ring psum's ~2·size·4 — the 4x codec saving plus the gather/
    ring factor; accuracy is per-step quantization noise, which
    `DistributedOptimizer(error_feedback=True)` recovers across steps."""
    q, scale = int8_encode(x)
    qs = lax.all_gather(q, axis_name)          # (n, *shape) int8
    ss = lax.all_gather(scale, axis_name)      # (n,) fp32
    ss = ss.reshape((ss.shape[0],) + (1,) * x.ndim)
    out = jnp.sum(qs.astype(jnp.float32) * ss, axis=0)
    from ..common import telemetry

    telemetry.counter(
        "horovod_traced_compressed_ops_total",
        "Traced allreduces compiled with a pre-psum wire cast "
        "(counted at trace time, labeled by codec)",
        labels={"codec": "int8"},
    ).inc()
    return out.astype(x.dtype)


def _scale(x, factor):
    if factor is None or factor == 1.0:
        return x
    # Float tensors scale in their own dtype; integer tensors go through
    # f32 so AVERAGE's 1/size postscale doesn't truncate to zero
    # (ref: ScaleBuffer int dispatch, collective_operations.h:89-125).
    if jnp.issubdtype(x.dtype, jnp.integer):
        return (x.astype(jnp.float32) * factor).astype(x.dtype)
    return x * jnp.asarray(factor, dtype=x.dtype)


def allreduce(
    tensor,
    axis_name: str,
    op: ReduceOp = ReduceOp.AVERAGE,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """All-reduce over a named mesh axis.

    AVERAGE is implemented as SUM with postscale 1/size, matching the
    reference (ref: operations.cc:851-858); Adasum uses the scaling-
    insensitive VHDD combination (see ops/adasum.py).
    """
    x = _scale(tensor, prescale_factor)
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        if _traced_int8_enabled(x, op):
            out = _int8_allreduce(x, axis_name)
        else:
            wire_dt = _traced_wire_dtype(x, op)
            if wire_dt is not None:
                out = lax.psum(x.astype(wire_dt), axis_name).astype(x.dtype)
            else:
                out = lax.psum(x, axis_name)
        if op == ReduceOp.AVERAGE:
            n = _axis_size(axis_name)
            out = _scale(out, 1.0 / n)
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        gathered = lax.all_gather(x, axis_name)
        out = jnp.prod(gathered, axis=0)
    elif op == ReduceOp.ADASUM:
        from .adasum import adasum_allreduce

        out = adasum_allreduce(x, axis_name)
    else:
        raise ValueError(f"unsupported reduce op: {op}")
    return _scale(out, postscale_factor)


def grouped_allreduce(
    tensors: Sequence,
    axis_name: str,
    op: ReduceOp = ReduceOp.AVERAGE,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """Fused all-reduce of a list of tensors (ref: grouped allreduce,
    horovod/torch/mpi_ops.py grouped_allreduce; fusion semantics of
    controller.cc:686-809).

    Under XLA a single psum over a flat concatenated buffer produces one
    large ICI all-reduce — the same wire behavior the reference's fusion
    buffer achieves with explicit memcpys, minus the copies when XLA
    elides them.
    """
    if not tensors:
        return []
    shapes = [t.shape for t in tensors]
    sizes = [int(jnp.size(t)) for t in tensors]
    dtypes = [t.dtype for t in tensors]
    widest = jnp.result_type(*dtypes)
    flat = jnp.concatenate(
        [jnp.ravel(t).astype(widest) for t in tensors]
    )
    red = allreduce(flat, axis_name, op, prescale_factor, postscale_factor)
    out, off = [], 0
    for shape, size, dt in zip(shapes, sizes, dtypes):
        out.append(jnp.reshape(red[off : off + size], shape).astype(dt))
        off += size
    return out


def allreduce_pytree(
    tree,
    axis_name: str,
    op: ReduceOp = ReduceOp.AVERAGE,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    fuse: bool = False,
):
    """All-reduce every leaf of a pytree (gradient trees)."""
    leaves, treedef = jax.tree.flatten(tree)
    if fuse:
        red = grouped_allreduce(leaves, axis_name, op, prescale_factor, postscale_factor)
    else:
        red = [allreduce(l, axis_name, op, prescale_factor, postscale_factor) for l in leaves]
    return jax.tree.unflatten(treedef, red)


def allgather(tensor, axis_name: str):
    """Concatenate each rank's tensor along dim 0 (ref: AllgatherOp,
    collective_operations.h:148-185; variable first-dim supported in the
    eager engine; under jit shapes are static so all ranks' first dims are
    equal by construction)."""
    return lax.all_gather(tensor, axis_name, tiled=True)


def broadcast(tensor, root_rank: int, axis_name: str):
    """Broadcast root's value to all ranks (ref: BroadcastOp,
    mpi_operations.cc:357-390). Implemented as a masked psum — a single
    ICI all-reduce, which XLA lowers efficiently; avoids materializing an
    all_gather."""
    idx = _axis_index(axis_name)
    mask = (idx == root_rank).astype(tensor.dtype)
    return lax.psum(tensor * mask, axis_name).astype(tensor.dtype)


def alltoall(tensor, axis_name: str, split_axis: int = 0, concat_axis: int = 0):
    """Equal-split all-to-all (ref: AlltoallOp, collective_operations.h:
    206-256). The leading dim must be divisible by the axis size; uneven
    splits are an eager-engine feature (dynamic shapes don't jit).
    This is the MoE dispatch / Ulysses sequence-exchange primitive."""
    n = _axis_size(axis_name)
    if tensor.shape[split_axis] % n != 0:
        raise ValueError(
            f"alltoall under jit requires dim {split_axis} divisible by axis size {n}"
        )
    return lax.all_to_all(
        tensor, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def reducescatter(tensor, axis_name: str, op: ReduceOp = ReduceOp.SUM):
    """Reduce-scatter along dim 0 (tiled). The building block of the
    hierarchical allreduce (ref: nccl_operations.cc:190-405) and of
    ZeRO/FSDP-style sharded optimizers."""
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports SUM/AVERAGE")
    out = lax.psum_scatter(tensor, axis_name, scatter_dimension=0, tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / _axis_size(axis_name)
    return out


def barrier(axis_name: str):
    """(ref: BarrierOp / controller Barrier) — a scalar psum forces a
    cross-chip sync point in the XLA program."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)


def axis_rank(axis_name: str):
    return _axis_index(axis_name)


def hierarchical_allreduce(
    tensor,
    inner_axis: str,
    outer_axis: str,
    op: ReduceOp = ReduceOp.AVERAGE,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """Two-level allreduce: reduce-scatter over the fast inner axis (ICI),
    all-reduce the shard over the slow outer axis (DCN), then all-gather
    back over ICI — the TPU mapping of NCCLHierarchicalAllreduce
    (ref: nccl_operations.cc:190-405: intra-node ncclReduceScatter → cross-
    node MPI_Allreduce → intra-node ncclAllGather)."""
    x = _scale(tensor, prescale_factor)
    orig_shape = x.shape
    flat = jnp.ravel(x)
    n_inner = _axis_size(inner_axis)
    pad = (-flat.size) % n_inner
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, inner_axis, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, outer_axis)
    full = lax.all_gather(shard, inner_axis, tiled=True)
    if pad:
        full = full[: flat.size - pad]
    out = jnp.reshape(full, orig_shape)
    if op == ReduceOp.AVERAGE:
        total = _axis_size(inner_axis) * _axis_size(outer_axis)
        out = _scale(out, 1.0 / total)
    elif op != ReduceOp.SUM:
        raise ValueError("hierarchical_allreduce supports SUM/AVERAGE")
    return _scale(out, postscale_factor)
