"""Fused BatchNorm-apply + ReLU + 1x1-conv + output-stats Pallas kernel.

Why: the ResNet-50 train step on TPU is HBM-bandwidth bound in the
BatchNorm passes, not MXU bound (see docs/benchmarks.md). XLA keeps
BN-apply and batch-stat reductions as separate passes over the
activations because it cannot fuse elementwise prologues/reduction
epilogues INTO a convolution. A 1x1 convolution is a plain matmul over
the channel dim, so Pallas can: this kernel reads the RAW (pre-BN)
input once, normalizes + ReLUs it in VMEM, feeds the MXU, and
accumulates the output's batch statistics (sum, sum-of-squares) in the
same pass — eliminating the normalize write+read and the stats read
that XLA pays around every 1x1 conv.

The reference has no analogue (its cuDNN convs are monolithic); this is
the "fuse elementwise into matmuls" TPU playbook applied to the BN
sandwich. Gradient support composes via jax.custom_vjp with the
reference composition's VJP (bwd fusion is follow-up work).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _reference_bn_relu_matmul(x, mu, var, gamma, beta, w, eps):
    """Unfused composition (also the custom_vjp's differentiation
    target): y = relu(bn(x)) @ w, plus batch stats of y."""
    xf = x.astype(jnp.float32)
    xhat = (xf - mu) * jax.lax.rsqrt(var + eps)
    a = jax.nn.relu(xhat * gamma + beta).astype(x.dtype)
    y = jnp.dot(a, w, preferred_element_type=jnp.float32)
    s1 = jnp.sum(y, axis=0)
    s2 = jnp.sum(y * y, axis=0)
    return y.astype(x.dtype), s1, s2


def fused_bn_relu_matmul(
    x: jax.Array,          # (M, Cin) raw pre-BN values (bf16/f32)
    mu: jax.Array,         # (Cin,) f32 batch mean of x
    var: jax.Array,        # (Cin,) f32 batch variance of x
    gamma: jax.Array,      # (Cin,) f32
    beta: jax.Array,       # (Cin,) f32
    w: jax.Array,          # (Cin, Cout)
    *,
    eps: float = 1e-5,
    block_m: int = 512,
    block_n: int = 256,
    interpret: Optional[bool] = None,
    accum: str = "scratch",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (y, sum(y, 0), sum(y*y, 0)) with y = relu(bn(x)) @ w.

    One pass over x and one write of y; the stats ride the matmul
    epilogue. M and Cout must be multiples of the block sizes (the
    ResNet shapes are).

    accum="scratch" (default): grid is (i, j) with j INNERMOST, so the
    x block's index map is constant across the inner sweep and Pallas
    never re-fetches it — x truly streams ONCE. Stats accumulate in a
    (1, Cout) f32 VMEM scratch (persistent across grid steps on TPU)
    and are written to the outputs exactly once, on the last i row, so
    the revisited-output-block rule is never relied on.

    accum="revisit": grid (j, i) with the reduction dim innermost and
    output-block accumulation — the r4 correctness-fix layout; slower
    (x re-streams once per Cout block) but kept as the
    reference/fallback structure.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    M, Cin = x.shape
    Cout = w.shape[1]
    block_m = min(block_m, M)
    block_n = min(block_n, Cout)
    if M % block_m or Cout % block_n:
        raise ValueError(f"M={M} / Cout={Cout} not divisible by blocks "
                         f"({block_m}, {block_n})")
    n_i = M // block_m

    def _normalize(x_ref, mu_ref, var_ref, gamma_ref, beta_ref):
        xf = x_ref[...].astype(jnp.float32)
        rs = jax.lax.rsqrt(var_ref[...] + eps)
        return jnp.maximum(
            (xf - mu_ref[...]) * (rs * gamma_ref[...]) + beta_ref[...],
            0.0,
        ).astype(x_ref.dtype)

    if accum == "scratch":
        from jax.experimental.pallas import tpu as pltpu

        last = n_i - 1

        def kernel(x_ref, mu_ref, var_ref, gamma_ref, beta_ref, w_ref,
                   y_ref, s1_ref, s2_ref, s1_acc, s2_acc):
            i = pl.program_id(0)
            j = pl.program_id(1)
            # (A normalize-once VMEM cache of `a` across the j sweep
            # was benchmarked and REJECTED: the scratch store/load
            # costs more than recomputing the prologue at Cin<=256 —
            # 1.36x -> 1.08x on the winning shape — and only lifts the
            # Cin=512 shape to 0.98x, still short of XLA.)
            a = _normalize(x_ref, mu_ref, var_ref, gamma_ref, beta_ref)
            y = jnp.dot(a, w_ref[...],
                        preferred_element_type=jnp.float32)
            y_ref[...] = y.astype(y_ref.dtype)
            part1 = jnp.sum(y, axis=0, keepdims=True)
            part2 = jnp.sum(y * y, axis=0, keepdims=True)
            sl = pl.ds(j * block_n, block_n)

            @pl.when((i == 0) & (i != last))
            def _init():
                s1_acc[:, sl] = part1
                s2_acc[:, sl] = part2

            @pl.when((i != 0) & (i != last))
            def _acc():
                s1_acc[:, sl] += part1
                s2_acc[:, sl] += part2

            @pl.when((i == last) & (i != 0))
            def _final():
                s1_ref[...] = s1_acc[:, sl] + part1
                s2_ref[...] = s2_acc[:, sl] + part2

            @pl.when((i == last) & (i == 0))
            def _single():
                s1_ref[...] = part1
                s2_ref[...] = part2

        grid = (n_i, Cout // block_n)
        y, s1, s2 = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_m, Cin), lambda i, j: (i, 0)),
                pl.BlockSpec((Cin,), lambda i, j: (0,)),
                pl.BlockSpec((Cin,), lambda i, j: (0,)),
                pl.BlockSpec((Cin,), lambda i, j: (0,)),
                pl.BlockSpec((Cin,), lambda i, j: (0,)),
                pl.BlockSpec((Cin, block_n), lambda i, j: (0, j)),
            ],
            out_specs=[
                pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
                pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
                pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((M, Cout), x.dtype),
                jax.ShapeDtypeStruct((1, Cout), jnp.float32),
                jax.ShapeDtypeStruct((1, Cout), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((1, Cout), jnp.float32),
                pltpu.VMEM((1, Cout), jnp.float32),
            ],
            interpret=interpret,
        )(x, mu, var, gamma, beta, w)
        return y, s1[0], s2[0]

    def kernel(x_ref, mu_ref, var_ref, gamma_ref, beta_ref, w_ref,
               y_ref, s1_ref, s2_ref):
        # Reduction dim i INNERMOST: Pallas TPU only preserves a
        # revisited output block (s1/s2 depend on j alone) across
        # *consecutive* grid steps — with i outermost the stats would
        # be silently wrong on real TPU whenever Cout > block_n.
        i = pl.program_id(1)
        a = _normalize(x_ref, mu_ref, var_ref, gamma_ref, beta_ref)
        y = jnp.dot(a, w_ref[...], preferred_element_type=jnp.float32)
        y_ref[...] = y.astype(y_ref.dtype)
        part1 = jnp.sum(y, axis=0, keepdims=True)
        part2 = jnp.sum(y * y, axis=0, keepdims=True)

        @pl.when(i == 0)
        def _init():
            s1_ref[...] = part1
            s2_ref[...] = part2

        @pl.when(i != 0)
        def _acc():
            s1_ref[...] += part1
            s2_ref[...] += part2

    grid = (Cout // block_n, n_i)
    y, s1, s2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, Cin), lambda j, i: (i, 0)),
            pl.BlockSpec((Cin,), lambda j, i: (0,)),
            pl.BlockSpec((Cin,), lambda j, i: (0,)),
            pl.BlockSpec((Cin,), lambda j, i: (0,)),
            pl.BlockSpec((Cin,), lambda j, i: (0,)),
            pl.BlockSpec((Cin, block_n), lambda j, i: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda j, i: (i, j)),
            pl.BlockSpec((1, block_n), lambda j, i: (0, j)),
            pl.BlockSpec((1, block_n), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, Cout), x.dtype),
            jax.ShapeDtypeStruct((1, Cout), jnp.float32),
            jax.ShapeDtypeStruct((1, Cout), jnp.float32),
        ],
        interpret=interpret,
    )(x, mu, var, gamma, beta, w)
    return y, s1[0], s2[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def bn_relu_conv1x1(x, mu, var, gamma, beta, w, eps=1e-5):
    """Differentiable fused op: fwd is the Pallas single-pass kernel,
    bwd is the VJP of the reference composition (XLA-fused; kernel bwd
    is follow-up work). Shapes as fused_bn_relu_matmul."""
    return fused_bn_relu_matmul(x, mu, var, gamma, beta, w, eps=eps)


def _fwd(x, mu, var, gamma, beta, w, eps):
    out = fused_bn_relu_matmul(x, mu, var, gamma, beta, w, eps=eps)
    return out, (x, mu, var, gamma, beta, w)


def _bwd(eps, res, cts):
    x, mu, var, gamma, beta, w = res
    _, vjp = jax.vjp(
        lambda *a: _reference_bn_relu_matmul(*a, eps), x, mu, var, gamma,
        beta, w)
    return vjp(cts)


bn_relu_conv1x1.defvjp(_fwd, _bwd)
