"""Fused attention as a Pallas TPU kernel.

The hot op of every transformer in the zoo (GPT-2/BERT/ViT,
models/transformer.py) is attention; XLA materializes the (S, S) score
matrix in HBM for the dense path. This kernel streams k-blocks through
a running-softmax accumulator entirely in VMEM: scores never touch HBM,
both matmuls ride the MXU in the input dtype (bf16 fast path, f32
accumulate), and causal q-blocks skip every k-block above the diagonal
(ref: the CUDA fused-scale kernel is the reference's only hand-written
device code, horovod/common/ops/cuda/cuda_kernels.cu:25-77 — the
equivalent TPU move per SURVEY.md §2.7 is Pallas for ops XLA fusion
can't cover).

Measured on one TPU v5e chip (B=2, H=8, D=64, bf16): 2.5x faster than
the XLA dense path at S=4096 causal, 1.1x non-causal; parity at S=1024.
Enable per model with TransformerConfig(attn_impl="flash").

Semantics match parallel/ring.py's dense_attention exactly, including
the padding-mask convention (1 = attend, 0 = pad; fully-masked rows
yield zeros). The backward pass is a custom VJP that recomputes
attention with the jnp reference implementation: only the (B,S,H,D)
inputs are saved (flash-style recompute), but the recompute itself is
the DENSE path, so the backward step does materialize (B,H,S,S) scores
in HBM — training memory matches attn_impl="dense"; the VMEM-bounded
win applies to the forward/inference path. A blockwise Pallas backward
is the known follow-up.

Gradients therefore differentiate the same math; forward numerics agree
with the reference to bf16/f32 tolerance (asserted in
tests/test_flash_attention.py, incl. interpret mode on CPU).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BLOCK_Q = 128
NEG_INF = -1e30

try:  # Pallas import kept optional: CPU-only deployments without the
    # TPU plugin still import this module (interpret mode covers tests).
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale: float,
            causal: bool, block_q: int, block_k: int):
    """One (batch*head, q-block) grid step, streaming k-blocks.

    q_ref: (1, block_q, D); k_ref/v_ref: (1, S_pad, D) VMEM-resident;
    mask_ref: (1, 1, S_pad); o_ref: (1, block_q, D)

    Flash-style: a fori_loop folds (block_q, block_k) score tiles into a
    running (max, normalizer, accumulator) state, so peak VMEM for
    scores is O(block_q*block_k) regardless of S, and causal q-blocks
    skip every k-block entirely above the diagonal — the canonical
    ~2x FLOP saving for causal attention.
    """
    qi = pl.program_id(1)

    # Native-dtype matmuls with f32 accumulation: bf16 inputs hit the
    # MXU's fast path; only the accumulator/softmax run in f32.
    q = q_ref[0]                               # (block_q, D)
    D = q.shape[-1]
    s_pad = k_ref.shape[1]

    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        acc, m, l = carry
        # Ref-level dynamic slices (Mosaic lowers pl.ds on refs; value-
        # level lax.dynamic_slice is not supported in-kernel).
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        m_blk = mask_ref[0, 0, pl.ds(kb * block_k, block_k)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                               # (block_q, block_k) f32
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = m_blk[None, :] > 0              # padded keys masked here
        if causal:
            valid = jnp.logical_and(valid, kpos <= qpos)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        # Explicit zeroing: an all-masked tile would otherwise turn the
        # NEG_INF plateau into exp(0)=1 rows (same convention as
        # parallel/ring.py _flash_block_update).
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * corr[:, None] + pv
        return acc, m_new, l

    num_kb = s_pad // block_k
    if causal:
        # k-blocks whose first key position exceeds this q-block's last
        # query position are entirely masked: skip them.
        last_q = (qi + 1) * block_q - 1
        num_kb = jnp.minimum(num_kb, last_q // block_k + 1)

    acc = jnp.zeros((block_q, D), jnp.float32)
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc, m, l))

    o = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = o.astype(o_ref.dtype)


DEFAULT_BLOCK_K = 512


def _flash_fwd(q, k, v, mask, causal: bool, block_q: int,
               interpret: bool) -> jax.Array:
    B, S, H, D = q.shape
    scale = 1.0 / float(np.sqrt(D))
    bq = min(block_q, S)
    bk = min(DEFAULT_BLOCK_K, S)
    # Pad queries to a bq multiple (garbage rows sliced off after) and
    # keys/values to a bk multiple (padded keys carry mask 0, so they
    # never contribute).
    pad_q = (-S) % bq
    pad_k = (-S) % bk

    # (B, S, H, D) -> (B*H, S, D): attention is independent per (b, h).
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    qb, kb_arr, vb = to_bh(q), to_bh(k), to_bh(v)
    if pad_q:
        qb = jnp.pad(qb, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kb_arr = jnp.pad(kb_arr, ((0, 0), (0, pad_k), (0, 0)))
        vb = jnp.pad(vb, ((0, 0), (0, pad_k), (0, 0)))
    Sq, Sk = S + pad_q, S + pad_k

    # (B, 1, Sk): the singleton sublane dim satisfies Mosaic's tiling
    # rule for the (1, 1, Sk) block (last two dims must divide (8, 128)
    # or equal the array dims).
    if mask is None:
        mask2 = jnp.ones((B, 1, S), jnp.float32)
    else:
        mask2 = mask.astype(jnp.float32).reshape(B, 1, S)
    if pad_k:
        mask2 = jnp.pad(mask2, ((0, 0), (0, 0), (0, pad_k)))

    grid = (B * H, Sq // bq)
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
            # mask indexed by batch = bh // H (static H via closure).
            pl.BlockSpec((1, 1, Sk), lambda bh, qi, H=H: (bh // H, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(qb, kb_arr, vb, mask2)

    out = out[:, :S]
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _reference(q, k, v, mask, causal):
    """jnp reference (identical math; used for the recompute backward)."""
    from ..parallel.ring import dense_attention

    return dense_attention(q, k, v, causal=causal, mask=mask)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, mask=None, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    interpret: Optional[bool] = None):
    """Fused attention. q/k/v: (B, S, H, D); mask: optional (B, S) key
    validity (1 = attend). Returns (B, S, H, D) in q.dtype.

    `interpret=None` auto-selects: compiled Pallas on TPU, interpreter
    elsewhere (so CPU tests and the 8-device virtual mesh still run)."""
    if not HAVE_PALLAS:
        raise ImportError(
            "flash_attention needs jax.experimental.pallas; use "
            "attn_impl='dense' (or ring/ulysses) on this installation"
        )
    return _flash_fwd(q, k, v, mask, causal, block_q,
                      _resolve_interpret(interpret))


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover
        return True


def _fwd(q, k, v, mask, causal, block_q, interpret):
    out = _flash_fwd(q, k, v, mask, causal, block_q,
                     _resolve_interpret(interpret))
    return out, (q, k, v, mask)


def _bwd(causal, block_q, interpret, residuals, g):
    q, k, v, mask = residuals
    # Flash-style recompute: differentiate the identical-math jnp
    # reference; XLA fuses this into its own attention backward.
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, mask, causal),
                     q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
