"""Fused attention as a Pallas TPU kernel.

The hot op of every transformer in the zoo (GPT-2/BERT/ViT,
models/transformer.py) is attention; XLA materializes the (S, S) score
matrix in HBM for the dense path. This kernel streams k-blocks through
a running-softmax accumulator entirely in VMEM: scores never touch HBM,
both matmuls ride the MXU in the input dtype (bf16 fast path, f32
accumulate), and causal q-blocks skip every k-block above the diagonal
(ref: the CUDA fused-scale kernel is the reference's only hand-written
device code, horovod/common/ops/cuda/cuda_kernels.cu:25-77 — the
equivalent TPU move per SURVEY.md §2.7 is Pallas for ops XLA fusion
can't cover).

Measured on one TPU v5e chip (H=8, D=64, bf16, causal): forward 2.5x
the XLA dense path at S=4096; forward+backward 2.3x at S=4096 and ~20x
at S=8192 (where dense spills its (S, S) scores to HBM). Enable per
model with TransformerConfig(attn_impl="flash").

Semantics match parallel/ring.py's dense_attention exactly, including
the padding-mask convention (1 = attend, 0 = pad; fully-masked rows
yield zeros). The backward pass is blockwise Pallas too (Dao et al.
structure): the forward saves only the output and the per-row
logsumexp, and ONE fused kernel (`_dqkv_kernel`, r5) recomputes each
probability tile exactly once while producing dQ, dK, and dV in a
single k-block sweep (dQ rides a persistent VMEM scratch) — so
neither direction ever materializes (S, S) scores in HBM, causal
block-skipping applies in both, and the backward does 5 tile matmuls
instead of the classic two-pass structure's 7. Tiles that cannot be
touched by masking (below-diagonal, no padding) take a stripped
VPU-light body — see `_prep`'s `plain`.

Gradients therefore differentiate the same math; forward numerics agree
with the reference to bf16/f32 tolerance (asserted in
tests/test_flash_attention.py, incl. interpret mode on CPU).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BLOCK_Q = 128
NEG_INF = -1e30

def _prec(dtype):
    """Explicit contract precision for in-kernel dots: bf16 (and other
    sub-f32) inputs must use the native MXU path — a global
    jax_default_matmul_precision=float32 would otherwise inject an
    fp32-precision bf16 matmul that Mosaic rejects ("Bad lhs type").
    f32 inputs keep None so the global config still applies to them."""
    return None if dtype == jnp.float32 else jax.lax.Precision.DEFAULT


try:  # Pallas import kept optional: CPU-only deployments without the
    # TPU plugin still import this module (interpret mode covers tests).
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *, scale: float,
            causal: bool, block_q: int, block_k: int, plain: bool):
    """One (batch*head, q-block) grid step, streaming k-blocks.

    q_ref: (1, block_q, D); k_ref/v_ref: (1, S_pad, D) VMEM-resident;
    mask_ref: (1, 1, S_pad); o_ref: (1, block_q, D);
    lse_ref: (1, 1, block_q) per-row logsumexp residual

    Flash-style: a fori_loop folds (block_q, block_k) score tiles into a
    running (max, normalizer, accumulator) state, so peak VMEM for
    scores is O(block_q*block_k) regardless of S, and causal q-blocks
    skip every k-block entirely above the diagonal — the canonical
    ~2x FLOP saving for causal attention.

    `plain=True` (no padding mask, keys unpadded): tiles fully below the
    diagonal take a mask-free body — no position iotas, compares, or
    where-selects. At D=64 the per-score softmax VPU work, not the MXU,
    bounds this kernel (docs/benchmarks.md), so stripping the masking
    VPU ops from the ~60% of tiles that never needed them is a direct
    win; only the tiles straddling the diagonal run the masked body.
    """
    qi = pl.program_id(1)

    # Native-dtype matmuls with f32 accumulation: bf16 inputs hit the
    # MXU's fast path; only the accumulator/softmax run in f32.
    q = q_ref[0]                               # (block_q, D)
    D = q.shape[-1]
    s_pad = k_ref.shape[1]

    def tile(kb, carry, masked):
        acc, m, l = carry
        # Ref-level dynamic slices (Mosaic lowers pl.ds on refs; value-
        # level lax.dynamic_slice is not supported in-kernel).
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(q.dtype),
        ) * scale                               # (block_q, block_k) f32
        if masked:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if plain:
                valid = kpos <= qpos
            else:
                m_blk = mask_ref[0, 0, pl.ds(kb * block_k, block_k)]
                valid = m_blk[None, :] > 0      # padded keys masked here
                if causal:
                    valid = jnp.logical_and(valid, kpos <= qpos)
            s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if masked:
            # Explicit zeroing: an all-masked tile would otherwise turn
            # the NEG_INF plateau into exp(0)=1 rows (same convention as
            # parallel/ring.py _flash_block_update).
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(v_blk.dtype),
        )
        acc = acc * corr[:, None] + pv
        return acc, m_new, l

    num_kb = s_pad // block_k
    if causal:
        # k-blocks whose first key position exceeds this q-block's last
        # query position are entirely masked: skip them.
        last_q = (qi + 1) * block_q - 1
        num_kb = jnp.minimum(num_kb, last_q // block_k + 1)

    carry = (
        jnp.zeros((block_q, D), jnp.float32),
        jnp.full((block_q,), NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    if plain and causal:
        # Tiles whose last key row sits at/below this q-block's first
        # query row need no causal masking at all.
        n_full = (qi * block_q) // block_k
        carry = jax.lax.fori_loop(
            0, n_full, lambda kb, c: tile(kb, c, masked=False), carry)
        carry = jax.lax.fori_loop(
            n_full, num_kb, lambda kb, c: tile(kb, c, masked=True), carry)
    elif plain:
        carry = jax.lax.fori_loop(
            0, num_kb, lambda kb, c: tile(kb, c, masked=False), carry)
    else:
        carry = jax.lax.fori_loop(
            0, num_kb, lambda kb, c: tile(kb, c, masked=True), carry)
    acc, m, l = carry

    l_safe = jnp.maximum(l, 1e-30)
    o = acc / l_safe[:, None]
    o_ref[0] = o.astype(o_ref.dtype)
    # Per-row logsumexp, the only residual the backward needs beyond the
    # inputs (Dao et al. flash backward): p = exp(s - L) is already
    # normalized.
    lse_ref[0, 0] = m + jnp.log(l_safe)


DEFAULT_BLOCK_K = 512


def _prep(q, k, v, mask, block_q: int):
    """Shared layout/padding for forward and backward: (B,S,H,D) ->
    (B*H,S,D) with queries padded to a block_q multiple (garbage rows
    sliced off after) and keys/values/mask padded to a block_k multiple
    (padded keys carry mask 0, so they never contribute). Both passes
    MUST use identical block/pad arithmetic for the saved lse residual
    to line up with the backward's blocks.

    Also returns `plain`: True when no padding mask exists and keys
    needed no block padding — the kernels then take the mask-free fast
    path on below-diagonal tiles (the key-validity mask is the only
    thing key padding relies on, so it must force the masked path)."""
    B, S, H, D = q.shape
    if block_q is None:
        # Measured on v5e (B4 H12 D64, full GPT-2 train step, r5,
        # mask-free fast path + fused single-sweep backward): at
        # S=2048, 256 wins (77.0 ms vs 81.4 at 512 and 96.6 at 128);
        # at S=4096, 512 stays ~25% ahead of 256 (coarser causal
        # skipping amortizes, VMEM pressure per q-block matters less).
        # Below 2048 the finer grid's causal skipping pays: 128. (384
        # and 1024 lose everywhere — Mosaic tiling/VMEM pressure.)
        if S < 2048:
            block_q = DEFAULT_BLOCK_Q
        elif S == 2048:
            block_q = 256
        else:
            block_q = 512
    bq = min(block_q, S)
    bk = min(DEFAULT_BLOCK_K, S)
    pad_q = (-S) % bq
    pad_k = (-S) % bk

    # (B, S, H, D) -> (B*H, S, D): attention is independent per (b, h).
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    qb, kb_arr, vb = to_bh(q), to_bh(k), to_bh(v)
    if pad_q:
        qb = jnp.pad(qb, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kb_arr = jnp.pad(kb_arr, ((0, 0), (0, pad_k), (0, 0)))
        vb = jnp.pad(vb, ((0, 0), (0, pad_k), (0, 0)))

    # (B, 1, Sk): the singleton sublane dim satisfies Mosaic's tiling
    # rule for the (1, 1, Sk) block (last two dims must divide (8, 128)
    # or equal the array dims).
    if mask is None:
        mask2 = jnp.ones((B, 1, S), jnp.float32)
    else:
        mask2 = mask.astype(jnp.float32).reshape(B, 1, S)
    if pad_k:
        mask2 = jnp.pad(mask2, ((0, 0), (0, 0), (0, pad_k)))
    plain = mask is None and pad_k == 0
    return (qb, kb_arr, vb, mask2, to_bh, bq, bk, S + pad_q, S + pad_k,
            plain)


def _flash_fwd(q, k, v, mask, causal: bool, block_q: int,
               interpret: bool) -> "tuple[jax.Array, jax.Array]":
    B, S, H, D = q.shape
    scale = 1.0 / float(np.sqrt(D))
    qb, kb_arr, vb, mask2, _, bq, bk, Sq, Sk, plain = _prep(q, k, v,
                                                            mask, block_q)
    grid = (B * H, Sq // bq)
    out, lse = pl.pallas_call(
        functools.partial(_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, plain=plain),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, Sq), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
            # mask indexed by batch = bh // H (static H via closure).
            pl.BlockSpec((1, 1, Sk), lambda bh, qi, H=H: (bh // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, bq), lambda bh, qi: (bh, 0, qi)),
        ],
        interpret=interpret,
    )(qb, kb_arr, vb, mask2)

    out = out[:, :S]
    # Slice lse to the real rows too, so the backward's re-pad is the
    # single true padding (padded-row lse is kernel garbage here).
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3), lse[:, :, :S]


def _dqkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                 dq_ref, dk_ref, dv_ref, dq_acc, *, scale: float,
                 causal: bool, block_q: int, block_k: int, plain: bool):
    """FUSED backward: grid (B*H, k-block), ki innermost. One sweep
    computes dK/dV for this k-block AND accumulates every q-block's dQ
    contribution into a persistent f32 VMEM scratch (written out once,
    on the last k-block) — so each probability tile is recomputed ONCE
    per backward instead of once per pass, and the dO@V^T `dp` matmul
    is shared between dQ and dK instead of being issued twice (5 tile
    matmuls vs the two-pass structure's 7, and half the exp/VPU work).
    Measured on the GPT-2 seq-2048 v5e step this is the difference
    between ~0.49 and >=0.50 MFU (docs/benchmarks.md).

    The scratch depends on TPU grid semantics: grid steps run
    sequentially with the last dim innermost, so dq_acc persists across
    the ki sweep of one (b, h) program and is re-zeroed at ki=0.
    Padded q rows carry lse=+inf, killing their p rows — which is what
    keeps the `plain` fast path valid under q padding."""
    ki = pl.program_id(1)
    k = k_ref[0]                                 # (bk, D)
    v = v_ref[0]
    D = k.shape[-1]
    sq_pad = q_ref.shape[1]
    num_kb = pl.num_programs(1)

    @pl.when(ki == 0)
    def _zero():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def tile(qi, carry, masked):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qi * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qi * block_q, block_q), :]
        L = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(q_blk.dtype),
        ) * scale                                # (bq, bk)
        p = jnp.exp(s - L[:, None])
        if masked:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            if plain:
                valid = kpos <= qpos
            else:
                m_blk = mask_ref[0, 0]           # (bk,)
                valid = m_blk[None, :] > 0
                if causal:
                    valid = jnp.logical_and(valid, kpos <= qpos)
            p = jnp.where(valid, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(do_blk.dtype),
        )                                        # (bk, D)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(v.dtype),
        )                                        # (bq, bk)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(q_blk.dtype),
        )                                        # (bk, D)
        dq_acc[pl.ds(qi * block_q, block_q), :] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(k.dtype),
        )                                        # (bq, D)
        return dk, dv

    num_qb = sq_pad // block_q
    start_qb = 0
    if causal:
        start_qb = (ki * block_k) // block_q
    carry = (jnp.zeros((block_k, D), jnp.float32),
             jnp.zeros((block_k, D), jnp.float32))
    if plain and causal:
        diag_end = jnp.minimum(
            ((ki + 1) * block_k + block_q - 1) // block_q, num_qb)
        carry = jax.lax.fori_loop(
            start_qb, diag_end, lambda qi, c: tile(qi, c, masked=True),
            carry)
        carry = jax.lax.fori_loop(
            diag_end, num_qb, lambda qi, c: tile(qi, c, masked=False),
            carry)
    elif plain:
        carry = jax.lax.fori_loop(
            0, num_qb, lambda qi, c: tile(qi, c, masked=False), carry)
    else:
        carry = jax.lax.fori_loop(
            start_qb, num_qb, lambda qi, c: tile(qi, c, masked=True),
            carry)
    dk, dv = carry
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)

    @pl.when(ki == num_kb - 1)
    def _flush_dq():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, mask, out, lse, g, causal: bool, block_q: int,
               interpret: bool):
    """Blockwise backward: same VMEM-bounded structure as the forward —
    the (S, S) score matrix is never materialized in HBM."""
    B, S, H, D = q.shape
    scale = 1.0 / float(np.sqrt(D))
    qb, kb_arr, vb, mask2, to_bh, bq, bk, Sq, Sk, plain = _prep(
        q, k, v, mask, block_q)
    pad_q = Sq - S
    dob, ob = to_bh(g), to_bh(out)
    if pad_q:
        zq = ((0, 0), (0, pad_q), (0, 0))
        dob, ob = jnp.pad(dob, zq), jnp.pad(ob, zq)
        # Padded q rows: lse=+big makes p = exp(s - lse) vanish, so they
        # contribute nothing to dK/dV (their own dq rows are sliced off).
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)),
                      constant_values=1e30)

    # delta = rowsum(dO * O) (tiny elementwise; jnp outside the kernel).
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1).reshape(B * H, 1, Sq)

    from jax.experimental.pallas import tpu as pltpu

    full_q = pl.BlockSpec((1, Sq, D), lambda bh, ki: (bh, 0, 0))
    row_q = pl.BlockSpec((1, 1, Sq), lambda bh, ki: (bh, 0, 0))
    blk_k = pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0))

    dq, dk, dv = pl.pallas_call(
        functools.partial(_dqkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, plain=plain),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype),
        ],
        grid=(B * H, Sk // bk),
        in_specs=[
            full_q,
            blk_k, blk_k,
            pl.BlockSpec((1, 1, bk), lambda bh, ki, H=H: (bh // H, 0, ki)),
            full_q, row_q, row_q,
        ],
        out_specs=[
            full_q,       # dq: one block per (b, h), flushed on last ki
            blk_k, blk_k,
        ],
        scratch_shapes=[pltpu.VMEM((Sq, D), jnp.float32)],
        interpret=interpret,
    )(qb, kb_arr, vb, mask2, dob, lse, delta)

    def from_bh(x, S_):
        return x[:, :S_].reshape(B, H, S_, D).transpose(0, 2, 1, 3)

    return from_bh(dq, S), from_bh(dk, S), from_bh(dv, S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, mask=None, causal: bool = True,
                    block_q: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Fused attention. q/k/v: (B, S, H, D); mask: optional (B, S) key
    validity (1 = attend). Returns (B, S, H, D) in q.dtype.

    `block_q=None` auto-selects by sequence length (128 below S=2048,
    256 at 2048, 512 beyond — measured full-train-step crossover on
    v5e, r5); both vjp passes resolve it identically in `_prep`. `interpret=None` auto-selects:
    compiled Pallas on TPU, interpreter elsewhere (so CPU tests and the
    8-device virtual mesh still run)."""
    if not HAVE_PALLAS:
        raise ImportError(
            "flash_attention needs jax.experimental.pallas; use "
            "attn_impl='dense' (or ring/ulysses) on this installation"
        )
    out, _ = _flash_fwd(q, k, v, mask, causal, block_q,
                        _resolve_interpret(interpret))
    return out


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover
        return True


def _fwd(q, k, v, mask, causal, block_q, interpret):
    out, lse = _flash_fwd(q, k, v, mask, causal, block_q,
                          _resolve_interpret(interpret))
    return out, (q, k, v, mask, out, lse)


def _bwd(causal, block_q, interpret, residuals, g):
    q, k, v, mask, out, lse = residuals
    dq, dk, dv = _flash_bwd(q, k, v, mask, out, lse, g, causal, block_q,
                            _resolve_interpret(interpret))
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
