"""Adasum: scaling-insensitive gradient combination.

Re-implementation of the reference's recursive vector-halving distance-
doubling Adasum (ref: horovod/common/ops/adasum/adasum.h:100-280 —
per-pair coefficients from dot(a,b), ||a||^2, ||b||^2; requires power-of-2
ranks, checked at the Python layer in the reference at
horovod/torch/mpi_ops.py:93-113).

TPU-native design: instead of MPI point-to-point vector halving, each
round exchanges the *current accumulated vector* with the XOR partner via
`lax.ppermute` and both partners apply the symmetric combination

    result = (1 - dot/(2*||a||^2)) * a  +  (1 - dot/(2*||b||^2)) * b

After log2(n) rounds every rank holds the identical Adasum result. The
bandwidth profile differs from VHDD (full vector per round instead of
halves) but rides ICI all-to-neighbor links; a reduce-scatter-based
halving variant is used for large tensors.

Numerics: the reference accumulates dot/norm in float64
(ref: adasum.h DispatchComputeDotAndNormSqrds). TPUs have no fast f64, so
we accumulate in float32 with `precision=HIGHEST` — documented deviation.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.compat import axis_index as _axis_index, axis_size as _axis_size


def _combine(a, b):
    """The Adasum pair combination (ref: adasum.h:100-140)."""
    af = jnp.ravel(a).astype(jnp.float32)
    bf = jnp.ravel(b).astype(jnp.float32)
    dot = jnp.dot(af, bf, precision=lax.Precision.HIGHEST)
    na = jnp.dot(af, af, precision=lax.Precision.HIGHEST)
    nb = jnp.dot(bf, bf, precision=lax.Precision.HIGHEST)
    # Guard zero norms exactly like the reference (skip projection term).
    ca = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)), 1.0)
    cb = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)), 1.0)
    return (ca * af + cb * bf).reshape(a.shape).astype(a.dtype)


def adasum_allreduce(tensor, axis_name: str):
    """Adasum over a named mesh axis; axis size must be a power of two."""
    n = _axis_size(axis_name)
    if n & (n - 1) != 0:
        raise ValueError(
            f"Adasum requires a power-of-2 axis size, got {n} "
            "(ref: horovod/torch/mpi_ops.py:93-113)"
        )
    x = tensor
    rounds = int(math.log2(n))
    idx = _axis_index(axis_name)
    for k in range(rounds):
        stride = 1 << k
        # XOR-partner exchange as a ppermute permutation.
        perm = [(i, i ^ stride) for i in range(n)]
        partner_x = lax.ppermute(x, axis_name, perm)
        # Deterministic operand order so both partners compute the same
        # floating-point result: lower rank's vector is `a`.
        is_lower = (idx & stride) == 0
        a = jnp.where(is_lower, x, partner_x)
        b = jnp.where(is_lower, partner_x, x)
        x = _combine(a, b)
    return x


def adasum_numpy(tensors):
    """NumPy reference of the same recursion — used by the eager engine's
    CPU backend and as the test oracle (mirrors the role of the NumPy
    model in ref: test/test_adasum_pytorch.py)."""
    n = len(tensors)
    assert n & (n - 1) == 0, "power-of-2 ranks required"
    vals = [np.asarray(t, dtype=np.float64) for t in tensors]
    rounds = int(math.log2(n))
    for k in range(rounds):
        stride = 1 << k
        new = [None] * n
        for i in range(n):
            j = i ^ stride
            a, b = (vals[i], vals[j]) if (i & stride) == 0 else (vals[j], vals[i])
            af, bf = a.ravel(), b.ravel()
            dot = float(af @ bf)
            na = float(af @ af)
            nb = float(bf @ bf)
            ca = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
            cb = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
            new[i] = (ca * a + cb * b)
        vals = new
    return [v.astype(np.asarray(t).dtype) for v, t in zip(vals, tensors)]
