"""Torch and Keras Spark estimators over the shared Store data path.

(ref: horovod/spark/torch/estimator.py:84-300 TorchEstimator/TorchModel,
horovod/spark/keras/estimator.py:106-544 KerasEstimator/KerasModel.)

Both reuse `JaxEstimator`'s pipeline shape: the DataFrame is
materialized once to store Parquet keyed by a content fingerprint,
every worker STREAMS its own shard row-group-at-a-time
(`Store.iter_parquet_batches`), per-epoch checkpoints go to the store
from rank 0 only, and resume is decided on rank 0 and broadcast. The
framework-specific parts — distributed optimizer wrapping, weight
broadcast, the train step — go through the `horovod_tpu.torch` /
`horovod_tpu.keras` bindings, exactly how a user of those bindings
would write the loop by hand.

Models ride the pickled worker closure: torch modules pickle natively;
Keras models are serialized to `.keras` bytes (architecture + weights,
compile=False) because Keras 3 models do not survive plain pickle.
"""
from __future__ import annotations

import io
import os
import tempfile
import uuid
from typing import Callable, List, Optional, Sequence

import numpy as np

from .store import Store


# ---------------------------------------------------------------------------
# Shared worker-side data plumbing


def _prepare_data(store: Store, df) -> str:
    path = store.get_train_data_path()
    if not (store.is_parquet_dataset(path)
            and store.matches_fingerprint(df, path)):
        store.save_data_frame(df, path)
    return path


def _collect(df, cols):
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    return {c: pdf[c].to_numpy() for c in cols}


_VAL_SEED = 0x5EED


def _chunk_val_mask(validation, chunk_index: int, pdf, n: int):
    """Validation mask for one streamed chunk. A column name selects
    truthy rows; a float fraction uses a per-chunk deterministic RNG
    (seeded by the chunk's position in the deterministic stream order,
    so the split is IDENTICAL every epoch — ref:
    horovod/spark/common/params.py `validation`)."""
    if validation is None:
        return np.zeros(n, dtype=bool)
    if isinstance(validation, str):
        return pdf[validation].to_numpy().astype(bool)
    return (np.random.RandomState(_VAL_SEED + chunk_index).rand(n)
            < float(validation))


def _shard_batches(store, data_path, feature_cols, label_col, batch_size,
                   epoch, rank, size, validation=None,
                   sample_weight_col=None, subset="train"):
    """Stream exactly-batch_size (plus one final ragged) batches of one
    worker's shard with a buffer-local shuffle; memory bounded by ~5x
    batch_size rows (see JaxEstimator.fit for the same construction).
    Yields (x, y, w) with w None when no sample_weight_col."""
    cols = list(feature_cols) + [label_col]
    if sample_weight_col:
        cols.append(sample_weight_col)
    if isinstance(validation, str):
        cols.append(validation)
    rng = np.random.RandomState(epoch)
    bufs: List = []
    have = 0

    def flush():
        X = np.concatenate([b[0] for b in bufs])
        Y = np.concatenate([b[1] for b in bufs])
        W = (np.concatenate([b[2] for b in bufs])
             if sample_weight_col else None)
        return X, Y, W

    for ci, pdf in enumerate(store.iter_parquet_batches(
            data_path, columns=cols, shard_rank=rank, shard_size=size,
            batch_rows=max(batch_size * 4, 1024))):
        n = len(pdf)
        vmask = _chunk_val_mask(validation, ci, pdf, n)
        keep = vmask if subset == "val" else ~vmask
        if not keep.any():
            continue
        bx = np.stack([pdf[c].to_numpy() for c in feature_cols],
                      axis=-1).astype(np.float32)[keep]
        by = pdf[label_col].to_numpy()[keep]
        bw = (pdf[sample_weight_col].to_numpy().astype(np.float32)[keep]
              if sample_weight_col else None)
        perm = rng.permutation(len(by))
        bufs.append((bx[perm], by[perm],
                     bw[perm] if bw is not None else None))
        have += len(by)
        while have >= batch_size:
            X, Y, W = flush()
            yield (X[:batch_size], Y[:batch_size],
                   W[:batch_size] if W is not None else None)
            bufs = [(X[batch_size:], Y[batch_size:],
                     W[batch_size:] if W is not None else None)]
            have -= batch_size
    if have:
        yield flush()


def _memory_batches(x, y, w, batch_size, epoch, steps):
    perm = np.random.RandomState(epoch).permutation(len(y))
    for i in range(max(steps, 1)):
        idx = perm[i * batch_size:(i + 1) * batch_size]
        yield x[idx], y[idx], (w[idx] if w is not None else None)


class _DataPlan:
    """Worker-side view of the training data: streaming from the store
    when one is configured, in-closure arrays otherwise. Handles the
    train/validation split (float fraction or indicator column) and the
    optional sample-weight column (ref:
    horovod/spark/common/params.py:30-106 validation /
    sample_weight_col)."""

    def __init__(self, est, df):
        self.store = est.store
        self.feature_cols = est.feature_cols
        self.label_col = est.label_col
        self.batch_size = est.batch_size
        self.validation = getattr(est, "validation", None)
        self.sample_weight_col = getattr(est, "sample_weight_col", None)
        if (self.validation is not None
                and not isinstance(self.validation, str)):
            f = float(self.validation)
            if not 0.0 < f < 1.0:
                raise ValueError(
                    f"validation fraction must be in (0, 1), got {f}")
        if self.store is not None:
            self.data_path = _prepare_data(self.store, df)
            self.data_fp = self.store.dataset_fingerprint(df)
            self.cols = None
        else:
            cols = list(self.feature_cols) + [self.label_col]
            if self.sample_weight_col:
                cols.append(self.sample_weight_col)
            if isinstance(self.validation, str):
                cols.append(self.validation)
            self.cols = _collect(df, cols)
            self.data_path = self.data_fp = None

    # everything below runs inside the worker --------------------------
    def _memory_arrays(self, rank, size, subset):
        y_all = self.cols[self.label_col]
        n = len(y_all)
        if self.validation is None:
            vmask = np.zeros(n, dtype=bool)
        elif isinstance(self.validation, str):
            vmask = self.cols[self.validation].astype(bool)
        else:
            vmask = (np.random.RandomState(_VAL_SEED).rand(n)
                     < float(self.validation))
        keep = vmask if subset == "val" else ~vmask
        x = np.stack([self.cols[c] for c in self.feature_cols],
                     axis=-1).astype(np.float32)[keep]
        y = y_all[keep]
        w = (self.cols[self.sample_weight_col].astype(np.float32)[keep]
             if self.sample_weight_col else None)
        sl = slice(rank, None, size)
        return x[sl], y[sl], (w[sl] if w is not None else None)

    def local_rows(self, rank, size, subset="train") -> int:
        if self.store is None:
            return len(self._memory_arrays(rank, size, subset)[1])
        if self.validation is None and subset == "train":
            return self.store.shard_num_rows(self.data_path, rank, size)
        # Subset counts need a mask pass; read only the cheap columns.
        cols = [self.validation] if isinstance(self.validation, str) \
            else [self.label_col]
        count = 0
        for ci, pdf in enumerate(self.store.iter_parquet_batches(
                self.data_path, columns=cols, shard_rank=rank,
                shard_size=size, batch_rows=max(self.batch_size * 4,
                                                1024))):
            vmask = _chunk_val_mask(self.validation, ci, pdf, len(pdf))
            count += int(vmask.sum() if subset == "val"
                         else (~vmask).sum())
        return count

    def batches(self, epoch, rank, size, subset="train"):
        """Yields (x, y, w); w is None without a sample_weight_col."""
        if self.store is not None:
            return _shard_batches(
                self.store, self.data_path, self.feature_cols,
                self.label_col, self.batch_size, epoch, rank, size,
                validation=self.validation,
                sample_weight_col=self.sample_weight_col, subset=subset)
        x, y, w = self._memory_arrays(rank, size, subset)
        steps = max(len(y) // self.batch_size, 1)
        return _memory_batches(x, y, w, self.batch_size, epoch, steps)


def _agreed_steps(hvd_mod, n_rows_local: int, batch_size: int) -> int:
    n = n_rows_local
    if hvd_mod.size() > 1:
        n = min(hvd_mod.allgather_object(n))
    return 0 if n == 0 else max(n // batch_size, 1)


def _decide_resume(hvd_mod, store, run_id, data_fp):
    """Rank 0 probes the store; the verdict is broadcast (per-rank
    probing desyncs epochs on non-shared mounts — see JaxEstimator)."""
    payload = (0, None)
    if hvd_mod.rank() == 0 and store is not None \
            and store.has_checkpoint(run_id):
        ckpt = store.load_checkpoint(run_id)
        if data_fp is None or ckpt.get("data_fp") == data_fp:
            payload = (int(ckpt.get("epoch", -1)) + 1, ckpt)
    if hvd_mod.size() > 1:
        payload = hvd_mod.broadcast_object(payload, root_rank=0,
                                           name="fw_estimator_resume")
    return payload


# ---------------------------------------------------------------------------
class TorchModel:
    """Fitted-model transformer (ref: torch/estimator.py:304 TorchModel)."""

    def __init__(self, model, feature_cols, label_col, output_col):
        self.model = model
        self.feature_cols = feature_cols
        self.label_col = label_col
        self.output_col = output_col

    def transform(self, df):
        import torch

        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        x = np.stack([pdf[c].to_numpy() for c in self.feature_cols],
                     axis=-1).astype(np.float32)
        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.from_numpy(x)).numpy()
        res = pdf.copy()
        res[self.output_col] = list(out)
        return res


class TorchEstimator:
    """Fit a torch.nn.Module on a DataFrame across Spark tasks
    (ref: horovod/spark/torch/estimator.py:84-231).

    `optimizer` is a torch optimizer INSTANCE (as in the reference);
    each worker rebuilds it against its own module copy — preserving
    per-param-group hyperparameters (distinct lrs, weight-decay groups)
    by mapping each group's params to their positions in
    `model.parameters()` — and wraps it in
    `horovod_tpu.torch.DistributedOptimizer`. The optimizer must have
    been constructed over parameters of the `model` passed in."""

    def __init__(self, model, optimizer, loss, feature_cols: Sequence[str],
                 label_col: str, output_col: str = "prediction",
                 num_proc: Optional[int] = None, epochs: int = 1,
                 batch_size: int = 32, store: Optional[Store] = None,
                 run_id: Optional[str] = None, validation=None,
                 sample_weight_col: Optional[str] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.output_col = output_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.store = store
        self.run_id = run_id or f"torch-estimator-{uuid.uuid4().hex[:8]}"
        # validation: float fraction in (0,1) for a deterministic split,
        # or a column name whose truthy rows are the validation set
        # (ref: horovod/spark/common/params.py:30-106). When
        # sample_weight_col is set, `loss` must return PER-SAMPLE losses
        # (shape [batch]); the estimator applies the weights and takes
        # the mean, matching Keras sample_weight semantics.
        self.validation = validation
        self.sample_weight_col = sample_weight_col

    def fit(self, df) -> TorchModel:
        # Closure captures PLAIN locals (not `self`): the worker payload
        # should carry the module, loss, and scalars — nothing else.
        plan = _DataPlan(self, df)
        module = self.model
        loss_fn = self.loss
        opt_cls = type(self.optimizer)
        # Per-param-group hyperparameters survive the worker rebuild:
        # each group is recorded as (hyperparams, positions into
        # model.parameters()) so distinct lrs / weight-decay groups are
        # reconstructed exactly (the reference serializes the optimizer
        # whole, ref: horovod/spark/torch/estimator.py:84-231).
        param_pos = {id(p): i for i, p in
                     enumerate(self.model.parameters())}
        opt_groups = []
        for g in self.optimizer.param_groups:
            try:
                idx = [param_pos[id(p)] for p in g["params"]]
            except KeyError:
                raise ValueError(
                    "TorchEstimator requires the optimizer to be "
                    "constructed over parameters of the model passed "
                    "in; found a param group referencing unknown "
                    "parameters"
                ) from None
            opt_groups.append(
                ({k: v for k, v in g.items() if k != "params"}, idx)
            )
        epochs, batch_size = self.epochs, self.batch_size
        store, run_id = self.store, self.run_id

        def train():
            import torch

            import horovod_tpu.torch as hvd

            hvd.init()
            model = module
            rank, size = hvd.rank(), hvd.size()

            start_epoch, ckpt = _decide_resume(
                hvd, store, run_id, plan.data_fp)
            if ckpt is not None:
                model.load_state_dict({
                    k: torch.from_numpy(np.asarray(v))
                    for k, v in ckpt["state_dict"].items()
                })
            plist = list(model.parameters())
            opt = opt_cls([
                dict(hp, params=[plist[i] for i in idx])
                for hp, idx in opt_groups
            ])
            if ckpt is not None and ckpt.get("opt_state") is not None:
                opt.load_state_dict(ckpt["opt_state"])
            hvd.broadcast_parameters(model.state_dict(), root_rank=0)
            hvd.broadcast_optimizer_state(opt, root_rank=0)
            opt = hvd.DistributedOptimizer(
                opt, named_parameters=model.named_parameters())

            def to_target(by):
                target = torch.from_numpy(np.asarray(by))
                if target.is_floating_point():
                    # pandas float columns default to float64;
                    # torch losses want the model's float32.
                    target = target.float()
                return target

            def batch_loss(bx, by, bw):
                loss = loss_fn(model(torch.from_numpy(bx)), to_target(by))
                if bw is not None:
                    if loss.dim() == 0:
                        raise ValueError(
                            "sample_weight_col requires `loss` to return "
                            "per-sample losses (shape [batch]); got a "
                            "scalar"
                        )
                    loss = (loss * torch.from_numpy(bw)).mean()
                elif loss.dim() > 0:
                    loss = loss.mean()
                return loss

            def rank_mean(v: float) -> float:
                # Per-epoch metric averaged across ranks (the
                # MetricAverageCallback semantics).
                return float(hvd.allreduce(
                    torch.tensor([v], dtype=torch.float64),
                    name="est_metric"))

            steps = _agreed_steps(hvd, plan.local_rows(rank, size),
                                  batch_size)
            val_steps = _agreed_steps(
                hvd, plan.local_rows(rank, size, "val"), batch_size
            ) if plan.validation is not None else 0
            history = {"loss": []}
            if val_steps:
                history["val_loss"] = []
            for epoch in range(start_epoch, epochs):
                model.train()
                it = plan.batches(epoch, rank, size)
                ep_loss = 0.0
                for _ in range(steps):
                    bx, by, bw = next(it)
                    opt.zero_grad()
                    loss = batch_loss(bx, by, bw)
                    loss.backward()
                    opt.step()
                    ep_loss += float(loss.detach())
                # steps is rank-agreed: every rank skips together, so
                # no fabricated 0.0 loss when there were no batches
                # (mirrors the Keras estimator).
                if steps:
                    history["loss"].append(rank_mean(ep_loss / steps))
                if val_steps:
                    model.eval()
                    vit = plan.batches(epoch, rank, size, subset="val")
                    v_loss = 0.0
                    with torch.no_grad():
                        for _ in range(val_steps):
                            vx, vy, vw = next(vit)
                            v_loss += float(batch_loss(vx, vy, vw))
                    history["val_loss"].append(
                        rank_mean(v_loss / val_steps))
                if store is not None and rank == 0:
                    store.save_checkpoint(run_id, {
                        "state_dict": {
                            k: v.detach().cpu().numpy()
                            for k, v in model.state_dict().items()
                        },
                        "opt_state": opt.state_dict(),
                        "epoch": epoch,
                        "data_fp": plan.data_fp,
                    }, epoch=epoch)
            return ({k: v.detach().cpu().numpy()
                     for k, v in model.state_dict().items()}, history)

        state_dict, history = _run_workers(train, self.num_proc, df)[0]
        import torch

        self.model.load_state_dict({
            k: torch.from_numpy(np.asarray(v))
            for k, v in state_dict.items()
        })
        fitted = TorchModel(self.model, self.feature_cols, self.label_col,
                            self.output_col)
        fitted.history = history
        return fitted


# ---------------------------------------------------------------------------
def _serialize_keras_model(model) -> bytes:
    """Keras-3 models don't pickle; `.keras` bytes do
    (ref: horovod/spark/keras/util.py serialize_model — same idea with
    h5 there)."""
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.keras")
        model.save(path)
        with open(path, "rb") as f:
            return f.read()


def _deserialize_keras_model(blob: bytes):
    import keras

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.keras")
        with open(path, "wb") as f:
            f.write(blob)
        return keras.models.load_model(path, compile=False)


class KerasModel:
    """Fitted-model transformer (ref: keras/estimator.py:544 KerasModel)."""

    def __init__(self, model, feature_cols, label_col, output_col):
        self.model = model
        self.feature_cols = feature_cols
        self.label_col = label_col
        self.output_col = output_col

    def transform(self, df):
        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        x = np.stack([pdf[c].to_numpy() for c in self.feature_cols],
                     axis=-1).astype(np.float32)
        out = np.asarray(self.model.predict(x, verbose=0))
        res = pdf.copy()
        res[self.output_col] = list(out)
        return res


class KerasEstimator:
    """Fit a Keras model on a DataFrame across Spark tasks
    (ref: horovod/spark/keras/estimator.py:106-543).

    `optimizer` is a keras optimizer instance (serialized via
    keras.optimizers.serialize and rebuilt per worker); `loss` is a
    Keras loss identifier or callable. Each worker compiles the model
    with `horovod_tpu.keras.DistributedOptimizer` and runs
    train_on_batch over its streamed shard."""

    def __init__(self, model, optimizer, loss, feature_cols: Sequence[str],
                 label_col: str, output_col: str = "prediction",
                 num_proc: Optional[int] = None, epochs: int = 1,
                 batch_size: int = 32, store: Optional[Store] = None,
                 run_id: Optional[str] = None, validation=None,
                 sample_weight_col: Optional[str] = None,
                 metrics: Optional[Sequence] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.output_col = output_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.store = store
        self.run_id = run_id or f"keras-estimator-{uuid.uuid4().hex[:8]}"
        # Same semantics as TorchEstimator; weights flow through Keras's
        # native train_on_batch(sample_weight=...) path
        # (ref: horovod/spark/common/params.py:30-106). `metrics` are
        # Keras metric identifiers compiled into the worker model; each
        # appears in history as its own rank-averaged per-epoch series
        # (ref: keras/estimator.py `metrics` param).
        self.validation = validation
        self.sample_weight_col = sample_weight_col
        self.metrics = list(metrics) if metrics else None

    def fit(self, df) -> KerasModel:
        import keras

        # Closure captures PLAIN locals only: Keras 3 model/optimizer
        # instances do not survive pickle, which is the whole reason
        # model_blob/opt_cfg exist — capturing `self` would smuggle the
        # live objects into the worker payload anyway.
        plan = _DataPlan(self, df)
        model_blob = _serialize_keras_model(self.model)
        opt_cfg = keras.optimizers.serialize(self.optimizer)
        loss = self.loss
        metrics = self.metrics
        epochs, batch_size = self.epochs, self.batch_size
        store, run_id = self.store, self.run_id

        def train():
            import keras

            import horovod_tpu.keras as hvd

            hvd.init()
            rank, size = hvd.rank(), hvd.size()
            model = _deserialize_keras_model(model_blob)

            start_epoch, ckpt = _decide_resume(
                hvd, store, run_id, plan.data_fp)
            if ckpt is not None:
                model.set_weights([np.asarray(w)
                                   for w in ckpt["weights"]])
            opt = hvd.DistributedOptimizer(
                keras.optimizers.deserialize(opt_cfg))
            model.compile(optimizer=opt, loss=loss, metrics=metrics)
            hvd.broadcast_global_variables(model, root_rank=0)

            # Series names: loss first, then the user's metrics in
            # declaration order (train_on_batch's return layout).
            # Derived from the estimator's `metrics` list, not
            # model.metrics_names — Keras 3 reports the container name
            # "compile_metrics" there, not the metric identifiers.
            def series_names(prefix=""):
                names = ["loss"] + [
                    m if isinstance(m, str)
                    else getattr(m, "name", str(m))
                    for m in (metrics or [])
                ]
                return [prefix + n for n in names]

            def rank_mean(v: float) -> float:
                import tensorflow as tf

                return float(hvd.allreduce(
                    tf.constant([v], dtype=tf.float64),
                    name="est_metric").numpy()[0])

            def as_vector(res) -> np.ndarray:
                # train/test_on_batch returns a scalar or [loss, *metrics]
                return np.asarray(res, dtype=np.float64).reshape(-1)

            steps = _agreed_steps(hvd, plan.local_rows(rank, size),
                                  batch_size)
            val_steps = _agreed_steps(
                hvd, plan.local_rows(rank, size, "val"), batch_size
            ) if plan.validation is not None else 0
            history: dict = {}
            for epoch in range(start_epoch, epochs):
                it = plan.batches(epoch, rank, size)
                ep = None
                for _ in range(steps):
                    bx, by, bw = next(it)
                    res = as_vector(model.train_on_batch(
                        bx, np.asarray(by), sample_weight=bw))
                    ep = res if ep is None else ep + res
                # steps is rank-agreed, so every rank skips together:
                # no fabricated 0.0 loss when there were no batches.
                if ep is not None:
                    for name, v in zip(series_names(), ep):
                        history.setdefault(name, []).append(
                            rank_mean(float(v) / steps))
                if val_steps:
                    vit = plan.batches(epoch, rank, size, subset="val")
                    vp = None
                    for _ in range(val_steps):
                        vx, vy, vw = next(vit)
                        res = as_vector(model.test_on_batch(
                            vx, np.asarray(vy), sample_weight=vw))
                        vp = res if vp is None else vp + res
                    for name, v in zip(series_names("val_"), vp):
                        history.setdefault(name, []).append(
                            rank_mean(float(v) / val_steps))
                if store is not None and rank == 0:
                    store.save_checkpoint(run_id, {
                        "weights": [np.asarray(w)
                                    for w in model.get_weights()],
                        "epoch": epoch,
                        "data_fp": plan.data_fp,
                    }, epoch=epoch)
            return ([np.asarray(w) for w in model.get_weights()],
                    history)

        weights, history = _run_workers(train, self.num_proc, df)[0]
        self.model.set_weights([np.asarray(w) for w in weights])
        fitted = KerasModel(self.model, self.feature_cols, self.label_col,
                            self.output_col)
        fitted.history = history
        return fitted


# ---------------------------------------------------------------------------
def _run_workers(train: Callable, num_proc: Optional[int], df):
    num_proc = num_proc or 1
    if hasattr(df, "rdd"):
        from .runner import run as spark_run

        return spark_run(train, num_proc=num_proc)
    if num_proc > 1:
        from ..runner import run as local_run

        return local_run(train, np=num_proc)
    return [train()]
