"""Spark integration (ref: horovod/spark/ — run()/run_elastic() +
Estimator API). pyspark is optional: `run(..., spark_context=...)`
accepts any object with the small RDD surface used, and JaxEstimator
fits pandas DataFrames locally."""
from .estimator import JaxEstimator, JaxModel
from .framework_estimators import (
    KerasEstimator,
    KerasModel,
    TorchEstimator,
    TorchModel,
)
from .runner import run, run_elastic
