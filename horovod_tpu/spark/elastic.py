"""Mid-job elastic rescale for Spark (ref: horovod/spark/runner.py:303
run_elastic + horovod/spark/driver/driver_service.py +
host_discovery.SparkDriverHostDiscovery).

The reference runs an elastic driver whose "hosts" are live Spark tasks:
each task registers with a driver service, the elastic driver execs
workers through the tasks, and Spark's task respawn supplies recovery.
The TPU port keeps that split but speaks the rendezvous KV instead of a
bespoke RPC:

  driver process                         spark task (executor)
  --------------                         ---------------------
  ElasticDriver                          _elastic_task_loop():
    SparkTaskDiscovery <- heartbeats  <-   heartbeat spark_task_alive/<host>
    create_worker() -> spawn cmd     ->    poll spark_cmd/<host>/<seq>
    SparkProcHandle.poll/wait <- status <- spawn/kill local subprocess,
                                           report spark_proc/<id>

Every object a task touches goes through the HTTP rendezvous client, so
the protocol is identical whether the task is a thread (the offline mock
barrier layer) or a real remote executor.
"""
from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional

from ..runner.elastic.discovery import HostDiscovery
from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

HEARTBEAT_INTERVAL = 0.3
HEARTBEAT_STALE = 3.0

_WORKER_MAIN = """\
import os, pickle, sys
with open(sys.argv[1], "rb") as f:
    fn = pickle.loads(f.read())
result = fn()
from horovod_tpu.backend.rendezvous import RendezvousClient
from horovod_tpu.utils import env as env_cfg
rank = int(os.environ["HOROVOD_RANK"])
c = RendezvousClient(env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
                     env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0))
c.put("spark_results", str(rank), pickle.dumps(result))
"""


# ---------------------------------------------------------------------------
# Driver side


class SparkTaskDiscovery(HostDiscovery):
    """Hosts = Spark tasks with a fresh heartbeat (ref:
    host_discovery.SparkDriverHostDiscovery — the task registry IS the
    discovery source; no script, no NIC probing)."""

    def __init__(self, server, max_np: int):
        self._server = server
        self._max_np = max_np

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        now = time.time()
        hosts: Dict[str, int] = {}
        for i in range(self._max_np):
            blob = self._server.handle_get(f"spark_task_alive/sparktask{i}")
            if blob is None:
                continue
            try:
                ts = float(blob.decode())
            except ValueError:
                continue
            if now - ts <= HEARTBEAT_STALE:
                hosts[f"sparktask{i}"] = 1
        return hosts


class SparkProcHandle:
    """Popen-shaped proxy for a worker subprocess living inside a Spark
    task; state rides the KV (the reference's task-service RPC client,
    ref: horovod/runner/common/service/task_service.py)."""

    def __init__(self, server, proc_id: str):
        self._server = server
        self._id = proc_id

    def poll(self) -> Optional[int]:
        blob = self._server.handle_get(f"spark_proc/{self._id}")
        if blob is None or blob == b"running":
            return None
        return int(blob.decode())

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(self._id, timeout)
            time.sleep(0.1)

    def _post_kill(self):
        self._server.handle_put(f"spark_kill/{self._id}", b"1")

    def terminate(self):
        self._post_kill()

    def kill(self):
        self._post_kill()


class SparkExecDriver:
    """Driver-side command fan-out: one monotonically numbered command
    stream per task host."""

    def __init__(self, server):
        self._server = server
        self._seq: Dict[str, int] = {}
        self._n = 0

    def spawn(self, hostname: str, env: Dict[str, str],
              run_id: str) -> SparkProcHandle:
        self._n += 1
        proc_id = f"{run_id}.{self._n}"
        seq = self._seq.get(hostname, 0)
        self._seq[hostname] = seq + 1
        cmd = pickle.dumps({"proc_id": proc_id, "env": env})
        self._server.handle_put(f"spark_cmd/{hostname}/{seq}", cmd)
        # Cursor handoff: a RESPAWNED task (Spark retry, same partition
        # index) must not replay stale spawn commands — it starts its
        # poll at the recorded head instead of 0.
        self._server.handle_put(f"spark_cmd_head/{hostname}",
                                str(seq + 1).encode())
        return SparkProcHandle(self._server, proc_id)

    def shutdown(self):
        self._server.handle_put("spark/shutdown", b"1")


# ---------------------------------------------------------------------------
# Task side (runs inside the Spark executor; KV access over HTTP only)


def _elastic_task_loop(index: int, driver_addr: str, driver_port: int):
    """Register, heartbeat, and execute spawn/kill commands until the
    driver announces shutdown (ref: horovod/spark/task/task_service.py
    run-command loop)."""
    from ..backend.rendezvous import RendezvousClient

    host = f"sparktask{index}"
    client = RendezvousClient(driver_addr, driver_port, timeout=300.0)

    # Fetch the payload once; workers read it from a task-local file.
    payload = client.wait_get("spark_payload", "fn")
    tmpdir = tempfile.mkdtemp(prefix=f"hvd-spark-{index}-")
    payload_path = os.path.join(tmpdir, "payload.pkl")
    with open(payload_path, "wb") as f:
        f.write(payload)
    main_path = os.path.join(tmpdir, "worker_main.py")
    with open(main_path, "w") as f:
        f.write(_WORKER_MAIN)

    # Cursor handoff BEFORE the first heartbeat: commands issued to a
    # dead predecessor of this partition index are stale and must not
    # be replayed (ghost workers with old-epoch env). Reading the head
    # before announcing liveness guarantees any spawn addressed to THIS
    # incarnation has seq >= head (the driver only targets hosts with
    # fresh heartbeats).
    head = client.get("spark_cmd_head", host)
    seq = int(head.decode()) if head is not None else 0

    # The slot hostname ("sparktaskN") is a logical identity; the TCP
    # data mesh needs this executor's REAL routable address. The
    # UDP-connect trick finds the interface that reaches the driver
    # (gethostbyname(gethostname()) is 127.0.1.1 on stock Debian).
    import socket as _socket

    try:
        probe = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        probe.connect((driver_addr, driver_port or 1))
        mesh_addr = probe.getsockname()[0]
        probe.close()
    except OSError:
        mesh_addr = "127.0.0.1"

    procs: Dict[str, subprocess.Popen] = {}
    last_beat = -1.0
    while True:
        now = time.time()
        slow_tick = now - last_beat >= HEARTBEAT_INTERVAL
        if slow_tick:
            client.put("spark_task_alive", host, str(now).encode())
            last_beat = now
            # Shutdown/kill ride the heartbeat cadence: per-iteration
            # polling would hammer the single rendezvous server with
            # thousands of requests/second at large max_np.
            if client.get("spark", "shutdown") is not None:
                break

        blob = client.get("spark_cmd", f"{host}/{seq}")
        if blob is not None:
            seq += 1
            cmd = pickle.loads(blob)
            proc_id, wenv = cmd["proc_id"], cmd["env"]
            env = dict(os.environ)
            env.update(wenv)
            env.setdefault("HOROVOD_MESH_ADDR", mesh_addr)
            env["PYTHONPATH"] = os.pathsep.join(
                [p for p in sys.path if p] +
                [env.get("PYTHONPATH", "")]
            ).rstrip(os.pathsep)
            p = subprocess.Popen(
                [sys.executable, main_path, payload_path], env=env
            )
            procs[proc_id] = p
            client.put("spark_proc", proc_id, b"running")

        for proc_id, p in list(procs.items()):
            if slow_tick and client.get("spark_kill",
                                        proc_id) is not None:
                if p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        p.kill()
            rc = p.poll()
            if rc is not None:
                client.put("spark_proc", proc_id, str(rc).encode())
                del procs[proc_id]

        time.sleep(0.05)

    for p in procs.values():
        if p.poll() is None:
            p.terminate()
    return index
