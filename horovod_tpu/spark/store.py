"""Storage layer for the estimator data path.

TPU-native re-design of the reference's Store
(ref: horovod/spark/common/store.py:29-433 — Store/FilesystemStore/
LocalStore/HDFSStore: a prefix path holding materialized Parquet
training data, per-run checkpoints, and logs; estimators materialize a
DataFrame to store Parquet once and every worker reads its shard from
there, ref: horovod/spark/common/util.py prepare_data).

Here `LocalStore` covers any locally-mounted filesystem (POSIX path or
``file://`` URL — on TPU-VMs GCS typically arrives via gcsfuse mounts,
so a mounted path is the common case); `FilesystemStore` generalizes
the same store over any `pyarrow.fs.FileSystem`, and `HDFSStore`
(r5) rides it via `pyarrow.fs.HadoopFileSystem` with the reference's
URL forms. ``gs://``/``s3://`` client layers remain out of scope —
`Store.create` says so explicitly rather than failing downstream
(mount, or hand `FilesystemStore` a pyarrow filesystem).
"""
from __future__ import annotations

import os
import pickle
import posixpath
import re
import shutil
import time
from typing import Any, List, Optional

from ..utils import atomic_file


class Store:
    """(ref: store.py:29-144 — path scheme + checkpoint/log IO.)"""

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_runs_path(self) -> str:
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes):
        raise NotImplementedError

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError

    def get_parquet_dataset(self, path: str):
        raise NotImplementedError

    def read_parquet(self, path: str, columns: Optional[List[str]] = None,
                     shard_rank: Optional[int] = None,
                     shard_size: Optional[int] = None):
        """Dataset (or one worker's shard of it) as a pandas DataFrame;
        the estimator's worker closure depends on this."""
        raise NotImplementedError

    def save_data_frame(self, df, path: str):
        raise NotImplementedError

    def sharding_by_parts(self, path: str, shard_size: int) -> bool:
        """Whether read_parquet(shard_rank=..., shard_size=...) returns
        disjoint per-rank shards (conservative default: no)."""
        return False

    def iter_parquet_batches(self, path: str,
                             columns: Optional[List[str]] = None,
                             shard_rank: Optional[int] = None,
                             shard_size: Optional[int] = None,
                             batch_rows: int = 1024):
        """Stream one worker's shard as bounded-size pandas chunks
        without ever materializing the shard (the TPU-native equivalent
        of the reference's Petastorm batch readers,
        ref: spark/common/util.py:697, keras/remote.py:336)."""
        raise NotImplementedError

    def shard_num_rows(self, path: str, shard_rank: Optional[int] = None,
                       shard_size: Optional[int] = None) -> int:
        """Exact row count of one worker's shard, from metadata only."""
        raise NotImplementedError

    def dataset_fingerprint(self, df) -> Optional[str]:
        """Cheap content identity for materialization reuse; None means
        'unknown — always re-materialize'."""
        return None

    def matches_fingerprint(self, df, path: str) -> bool:
        return False

    # -- checkpoint helpers (pickle pytrees; ref: keras/remote.py
    # checkpoint callbacks write per-epoch files) ----------------------
    def save_checkpoint(self, run_id: str, obj: Any, epoch: Optional[int] = None):
        path = self.get_checkpoint_path(run_id)
        if epoch is not None:
            base, ext = os.path.splitext(path)
            self.write(f"{base}.epoch{epoch}{ext}", pickle.dumps(obj))
        self.write(path, pickle.dumps(obj))

    def load_checkpoint(self, run_id: str) -> Any:
        return pickle.loads(self.read(self.get_checkpoint_path(run_id)))

    def has_checkpoint(self, run_id: str) -> bool:
        return self.exists(self.get_checkpoint_path(run_id))

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """(ref: store.py:141-146 Store.create dispatches on URL
        scheme.)"""
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith(("gs://", "s3://")):
            raise ValueError(
                f"remote filesystem URL {prefix_path!r} is not natively "
                "supported: mount it (gcsfuse) and pass the mounted "
                "path — the idiomatic arrangement on TPU-VMs — or "
                "construct FilesystemStore with a pyarrow.fs filesystem"
            )
        return LocalStore(prefix_path, *args, **kwargs)


class LocalStore(Store):
    """Filesystem-backed store (ref: store.py LocalStore:148-260; the
    same path scheme: <prefix>/intermediate_train_data,
    <prefix>/runs/<run_id>/{checkpoint, logs})."""

    FS_PREFIX = "file://"

    def __init__(self, prefix_path: str, train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 runs_path: Optional[str] = None):
        if prefix_path.startswith(self.FS_PREFIX):
            prefix_path = prefix_path[len(self.FS_PREFIX):]
        self.prefix_path = os.path.abspath(prefix_path)
        self._train_path = train_path or os.path.join(
            self.prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            self.prefix_path, "intermediate_val_data")
        self._runs_path = runs_path or os.path.join(self.prefix_path, "runs")
        os.makedirs(self.prefix_path, exist_ok=True)

    # -- paths ---------------------------------------------------------
    def _idx(self, path: str, idx: Optional[int]) -> str:
        return path if idx is None else f"{path}.{idx}"

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        return self._idx(self._train_path, idx)

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        return self._idx(self._val_path, idx)

    def get_runs_path(self) -> str:
        return self._runs_path

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint.pkl")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    # -- IO ------------------------------------------------------------
    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes):
        # Crash-safe tmp+rename via the shared helper (utils/atomic_file
        # — same protocol as the checkpoint shard writer and the
        # metrics/trace dumps): readers never see partial files.
        atomic_file.atomic_write_bytes(path, data)

    # -- parquet data path --------------------------------------------
    def is_parquet_dataset(self, path: str) -> bool:
        """(ref: store.py:167-175 — a directory of parquet part
        files, or a single parquet file.)"""
        if os.path.isfile(path):
            return path.endswith(".parquet")
        if not os.path.isdir(path):
            return False
        return any(
            n.endswith(".parquet") for n in os.listdir(path)
        ) or os.path.exists(os.path.join(path, "_SUCCESS"))

    def get_parquet_dataset(self, path: str):
        import pyarrow.parquet as pq

        return pq.ParquetDataset(path)

    # Filesystem hooks — FilesystemStore overrides these two to route
    # all parquet IO through an arbitrary pyarrow.fs.FileSystem while
    # inheriting the sharding math unchanged.
    def _open_parquet(self, path: str):
        import pyarrow.parquet as pq

        return pq.ParquetFile(path)

    def _read_table(self, path: str, columns: Optional[List[str]]):
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=columns)

    def read_parquet(self, path: str, columns: Optional[List[str]] = None,
                     shard_rank: Optional[int] = None,
                     shard_size: Optional[int] = None):
        """One worker's view of the dataset as a pandas DataFrame.

        Column pruning always applies. When the dataset has at least
        `shard_size` part files, each rank reads only parts
        rank::size (the reference's Petastorm readers similarly shard
        by row group, common/util.py); otherwise the caller must
        row-slice the returned frame itself."""
        parts = self._part_files(path)
        if (shard_rank is not None and shard_size is not None
                and shard_size > 1 and len(parts) >= shard_size):
            tables = [
                self._read_table(p, columns)
                for p in parts[shard_rank::shard_size]
            ]
            import pyarrow as pa

            return pa.concat_tables(tables).to_pandas()
        return self._read_table(path, columns).to_pandas()

    def sharding_by_parts(self, path: str, shard_size: int) -> bool:
        """True when read_parquet(shard_rank=..., shard_size=...) will
        return disjoint per-rank shards (enough part files)."""
        return shard_size > 1 and len(self._part_files(path)) >= shard_size

    def iter_parquet_batches(self, path: str,
                             columns: Optional[List[str]] = None,
                             shard_rank: Optional[int] = None,
                             shard_size: Optional[int] = None,
                             batch_rows: int = 1024):
        """Stream one worker's shard as pandas chunks of <= batch_rows
        rows, reading row-group-at-a-time so shards larger than RAM can
        train. With enough part files each rank streams only its own
        files; otherwise rows are strided rank::size by GLOBAL row
        index, so per-rank totals match `shard_num_rows` exactly (the
        estimator's collective step-count agreement depends on that)."""
        parts = self._part_files(path)
        sharded = (shard_rank is not None and shard_size is not None
                   and shard_size > 1)
        by_parts = sharded and len(parts) >= shard_size
        files = parts[shard_rank::shard_size] if by_parts else parts
        offset = 0
        for f in files:
            pf = self._open_parquet(f)
            try:
                for rb in pf.iter_batches(batch_size=batch_rows,
                                          columns=columns):
                    pdf = rb.to_pandas()
                    if sharded and not by_parts:
                        first = (-(offset - shard_rank)) % shard_size
                        pdf = pdf.iloc[first::shard_size]
                    offset += len(rb)
                    if len(pdf):
                        yield pdf
            finally:
                pf.close()

    def shard_num_rows(self, path: str, shard_rank: Optional[int] = None,
                       shard_size: Optional[int] = None) -> int:
        """Exact per-shard row count from Parquet metadata (no data
        read), matching iter_parquet_batches' sharding."""
        parts = self._part_files(path)
        sharded = (shard_rank is not None and shard_size is not None
                   and shard_size > 1)
        by_parts = sharded and len(parts) >= shard_size

        def rows(f):
            pf = self._open_parquet(f)
            try:
                return pf.metadata.num_rows
            finally:
                pf.close()

        if by_parts:
            return sum(rows(f) for f in parts[shard_rank::shard_size])
        total = sum(rows(f) for f in parts)
        if not sharded:
            return total
        return len(range(shard_rank, total, shard_size))

    def _part_files(self, path: str) -> List[str]:
        if os.path.isfile(path):
            return [path]
        if not os.path.isdir(path):
            return []
        return sorted(
            os.path.join(path, n) for n in os.listdir(path)
            if n.endswith(".parquet")
        )

    def save_data_frame(self, df, path: str):
        """Materialize a DataFrame (Spark or pandas) to store Parquet
        (ref: common/util.py prepare_data's
        df.write.parquet(train_data_path)). Writes a fingerprint marker
        so a later fit with different data re-materializes instead of
        silently training on stale rows."""
        fp = self.dataset_fingerprint(df)
        if hasattr(df, "write"):  # real pyspark DataFrame
            df.write.mode("overwrite").parquet(f"{self.FS_PREFIX}{path}")
        else:
            pdf = df.toPandas() if hasattr(df, "toPandas") else df
            if os.path.isdir(path):
                shutil.rmtree(path)
            os.makedirs(path, exist_ok=True)
            pdf.to_parquet(os.path.join(path, "part-00000.parquet"))
            # Spark-compatible completion marker.
            with open(os.path.join(path, "_SUCCESS"), "w"):
                pass
        if fp is not None:
            self.write(self._fingerprint_path(path), fp.encode())

    def _fingerprint_path(self, path: str) -> str:
        return f"{path}._fingerprint"

    def dataset_fingerprint(self, df) -> Optional[str]:
        if hasattr(df, "write"):
            # Spark DataFrames have no cheap content hash; None forces
            # re-materialization every fit (correct, if conservative).
            return None
        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        try:
            import pandas as pd

            h = pd.util.hash_pandas_object(pdf, index=False)
            return f"{len(pdf)}-{int(h.sum()) & 0xFFFFFFFFFFFFFFFF:x}"
        except Exception:
            return None

    def matches_fingerprint(self, df, path: str) -> bool:
        """True iff `path` holds a materialization of exactly `df`."""
        fp = self.dataset_fingerprint(df)
        if fp is None:
            return False
        mark = self._fingerprint_path(path)
        return self.exists(mark) and self.read(mark).decode() == fp


class FilesystemStore(LocalStore):
    """Store over an arbitrary `pyarrow.fs.FileSystem`
    (ref: store.py:148-260 FilesystemStore — the reference's
    pyarrow-based generalization that LocalStore and HDFSStore share).

    Inherits LocalStore's path scheme and all the sharding math; only
    the filesystem primitives are rerouted through the pyarrow fs. Any
    filesystem implementing that interface works — HDFS via
    `HadoopFileSystem`, tests via `LocalFileSystem`."""

    def __init__(self, prefix_path: str, fs=None,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 runs_path: Optional[str] = None,
                 url_prefix: Optional[str] = None):
        default_local = fs is None
        if fs is None:
            import pyarrow.fs as pafs

            fs = pafs.LocalFileSystem()
        self.fs = fs
        # URL scheme Spark executors can address this filesystem by
        # (e.g. "file://", "hdfs://nn:8020"). None = no Spark-visible
        # URL exists for this fs; save_data_frame then refuses Spark
        # DataFrames instead of silently writing executor-local files.
        self._url_prefix = ("file://" if default_local and
                           url_prefix is None else url_prefix)
        if prefix_path.startswith(self.FS_PREFIX):
            prefix_path = prefix_path[len(self.FS_PREFIX):]
        # No abspath: paths are rooted inside the target filesystem.
        self.prefix_path = prefix_path.rstrip("/") or "/"
        join = posixpath.join
        self._train_path = train_path or join(
            self.prefix_path, "intermediate_train_data")
        self._val_path = val_path or join(
            self.prefix_path, "intermediate_val_data")
        self._runs_path = runs_path or join(self.prefix_path, "runs")
        self.fs.create_dir(self.prefix_path, recursive=True)

    # -- path scheme over posix joins ---------------------------------
    def get_run_path(self, run_id: str) -> str:
        return posixpath.join(self._runs_path, run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return posixpath.join(self.get_run_path(run_id), "checkpoint.pkl")

    def get_logs_path(self, run_id: str) -> str:
        return posixpath.join(self.get_run_path(run_id), "logs")

    # -- filesystem primitives ----------------------------------------
    def _info(self, path: str):
        return self.fs.get_file_info(path)

    def exists(self, path: str) -> bool:
        import pyarrow.fs as pafs

        return self._info(path).type != pafs.FileType.NotFound

    def read(self, path: str) -> bytes:
        # Crash recovery: a writer that died between write()'s two moves
        # leaves the previous (valid) version at <path>.bak and nothing
        # at <path> — serve the backup rather than failing a resume that
        # would otherwise find "no checkpoint". Open-first (no
        # exists-then-open pre-check, which would TOCTOU-race write()'s
        # rename-aside window) and WITHOUT renaming: a mutating promote
        # here would race concurrent readers and break read-only
        # credentials. A reader that loses both races — path moved
        # aside, then the finished writer already deleted the backup —
        # retries the canonical path, where the new version now lives.
        try:
            with self.fs.open_input_stream(path) as f:
                return f.read()
        except FileNotFoundError:
            try:
                with self.fs.open_input_stream(f"{path}.bak") as f:
                    return f.read()
            except FileNotFoundError:
                with self.fs.open_input_stream(path) as f:
                    return f.read()

    def write(self, path: str, data: bytes):
        import pyarrow.fs as pafs

        self.fs.create_dir(posixpath.dirname(path), recursive=True)
        # Write-then-rename: rename is atomic on HDFS (and POSIX), so
        # readers never observe partial files — same guarantee as
        # LocalStore.write. HDFS rename does NOT overwrite an existing
        # destination (unlike os.replace / LocalFileSystem.move), so an
        # existing target — e.g. checkpoint.pkl rewritten every epoch —
        # is first RENAMED ASIDE to <path>.bak, not deleted: a crash
        # between the two moves leaves either the backup or the new file
        # on disk, never zero copies of the only checkpoint (read()
        # promotes a stranded backup). The backup is removed only after
        # the new file is in place; a failed promote restores it.
        # Single-writer paths (per-run checkpoint ownership) make the
        # fixed backup name safe.
        tmp = f"{path}.tmp.{os.getpid()}.{time.monotonic_ns()}"
        with self.fs.open_output_stream(tmp) as f:
            f.write(data)
        backup = None
        if self._info(path).type == pafs.FileType.File:
            backup = f"{path}.bak"
            if self._info(backup).type == pafs.FileType.File:
                # Stale backup from an interrupted earlier write; the
                # live <path> supersedes it.
                self.fs.delete_file(backup)
            self.fs.move(path, backup)
        try:
            self.fs.move(tmp, path)
        except BaseException:
            if backup is not None:
                try:
                    self.fs.move(backup, path)
                except OSError:  # pragma: no cover - double fault
                    pass
            raise
        if backup is not None:
            try:
                self.fs.delete_file(backup)
            except OSError:  # pragma: no cover - benign leak
                pass

    def is_parquet_dataset(self, path: str) -> bool:
        import pyarrow.fs as pafs

        info = self._info(path)
        if info.type == pafs.FileType.File:
            return path.endswith(".parquet")
        if info.type != pafs.FileType.Directory:
            return False
        return bool(self._part_files(path)) or self.exists(
            posixpath.join(path, "_SUCCESS"))

    def _part_files(self, path: str) -> List[str]:
        import pyarrow.fs as pafs

        info = self._info(path)
        if info.type == pafs.FileType.File:
            return [path]
        if info.type != pafs.FileType.Directory:
            return []
        sel = pafs.FileSelector(path)
        return sorted(
            fi.path for fi in self.fs.get_file_info(sel)
            if fi.type == pafs.FileType.File
            and fi.path.endswith(".parquet")
        )

    # -- parquet IO hooks ---------------------------------------------
    def get_parquet_dataset(self, path: str):
        import pyarrow.parquet as pq

        return pq.ParquetDataset(path, filesystem=self.fs)

    def _open_parquet(self, path: str):
        import pyarrow.parquet as pq

        return pq.ParquetFile(self.fs.open_input_file(path))

    def _read_table(self, path: str, columns: Optional[List[str]]):
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=columns, filesystem=self.fs)

    def save_data_frame(self, df, path: str):
        """(ref: common/util.py prepare_data → df.write.parquet; the
        pandas fallback writes one part through the pyarrow fs.)"""
        import pyarrow as pa
        import pyarrow.fs as pafs
        import pyarrow.parquet as pq

        fp = self.dataset_fingerprint(df)
        if hasattr(df, "write"):  # real pyspark DataFrame
            if self._url_prefix is None:
                raise ValueError(
                    "this FilesystemStore's pyarrow filesystem has no "
                    "Spark-addressable URL; pass url_prefix= (e.g. "
                    "'hdfs://namenode:8020') or materialize a pandas "
                    "DataFrame instead"
                )
            # The full URL (scheme + authority) — not the bare path —
            # so Spark executors write to the SAME filesystem this
            # store reads (ref: store.py path_prefix/get_full_path).
            df.write.mode("overwrite").parquet(
                f"{self._url_prefix}{path}")
        else:
            pdf = df.toPandas() if hasattr(df, "toPandas") else df
            if self._info(path).type == pafs.FileType.Directory:
                self.fs.delete_dir(path)
            self.fs.create_dir(path, recursive=True)
            pq.write_table(
                pa.Table.from_pandas(pdf),
                posixpath.join(path, "part-00000.parquet"),
                filesystem=self.fs,
            )
            with self.fs.open_output_stream(
                    posixpath.join(path, "_SUCCESS")):
                pass
        if fp is not None:
            self.write(self._fingerprint_path(path), fp.encode())


class HDFSStore(FilesystemStore):
    """HDFS-backed store (ref: store.py:263-433 HDFSStore). Accepts the
    reference's prefix forms — ``hdfs://namenode:8020/user/x``,
    ``hdfs:///user/x``, or ``/user/x`` — plus its connection kwargs,
    and talks to HDFS through `pyarrow.fs.HadoopFileSystem` (libhdfs).

    On hosts without a usable libhdfs (the common TPU-VM case), the
    constructor raises with the FUSE-mount guidance instead of failing
    downstream; pass ``fs=`` explicitly to use any stand-in
    `pyarrow.fs.FileSystem` (tests use `LocalFileSystem`)."""

    FS_PREFIX = "hdfs://"
    # prefix, host, port, path — the reference's URL shape (ref:
    # store.py:319 URL_PATTERN), expressed as a stricter hdfs-only re.
    _URL = re.compile(
        r"^(?:hdfs://)?(?:([^/:]+))?(?::(\d+))?(/.*)?$")

    def __init__(self, prefix_path: str, host: Optional[str] = None,
                 port: Optional[int] = None, user: Optional[str] = None,
                 kerb_ticket: Optional[str] = None,
                 extra_conf: Optional[dict] = None, fs=None, **kwargs):
        if prefix_path.startswith(self.FS_PREFIX):
            m = self._URL.match(prefix_path[len(self.FS_PREFIX):])
            url_host, url_port, path = m.groups() if m else (None, None, None)
        else:
            url_host, url_port, path = None, None, prefix_path
        if not path:
            raise ValueError(
                f"could not parse an HDFS path out of {prefix_path!r}")
        host = host or url_host or "default"
        port = port if port is not None else (
            int(url_port) if url_port else 0)
        # Spark-visible URL authority (ref: store.py:329 _url_prefix):
        # an explicit namenode rides along; 'default' defers to the
        # cluster's fs.defaultFS.
        kwargs.setdefault(
            "url_prefix",
            f"hdfs://{host}:{port}" if host != "default" and port
            else (f"hdfs://{host}" if host != "default" else "hdfs://"),
        )
        if fs is None:
            import pyarrow.fs as pafs

            try:
                fs = pafs.HadoopFileSystem(
                    host=host, port=port, user=user,
                    kerb_ticket=kerb_ticket, extra_conf=extra_conf)
            except Exception as e:
                raise RuntimeError(
                    "HDFSStore could not initialize libhdfs "
                    f"({e}); on TPU-VMs mount HDFS (hdfs-fuse) and use "
                    "LocalStore on the mounted path, or pass fs= with "
                    "any pyarrow.fs.FileSystem"
                ) from e
        super().__init__(path, fs=fs, **kwargs)
