"""Storage layer for the estimator data path.

TPU-native re-design of the reference's Store
(ref: horovod/spark/common/store.py:29-433 — Store/FilesystemStore/
LocalStore/HDFSStore: a prefix path holding materialized Parquet
training data, per-run checkpoints, and logs; estimators materialize a
DataFrame to store Parquet once and every worker reads its shard from
there, ref: horovod/spark/common/util.py prepare_data).

Here `LocalStore` covers any locally-mounted filesystem (POSIX path or
``file://`` URL — on TPU-VMs GCS typically arrives via gcsfuse mounts,
so a mounted path is the common case). A true ``hdfs://``/``gs://``
client layer is deliberately out of scope; `Store.create` says so
explicitly rather than failing downstream.
"""
from __future__ import annotations

import os
import pickle
import shutil
import time
from typing import Any, List, Optional


class Store:
    """(ref: store.py:29-144 — path scheme + checkpoint/log IO.)"""

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_runs_path(self) -> str:
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes):
        raise NotImplementedError

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError

    def get_parquet_dataset(self, path: str):
        raise NotImplementedError

    def read_parquet(self, path: str, columns: Optional[List[str]] = None,
                     shard_rank: Optional[int] = None,
                     shard_size: Optional[int] = None):
        """Dataset (or one worker's shard of it) as a pandas DataFrame;
        the estimator's worker closure depends on this."""
        raise NotImplementedError

    def save_data_frame(self, df, path: str):
        raise NotImplementedError

    def sharding_by_parts(self, path: str, shard_size: int) -> bool:
        """Whether read_parquet(shard_rank=..., shard_size=...) returns
        disjoint per-rank shards (conservative default: no)."""
        return False

    def iter_parquet_batches(self, path: str,
                             columns: Optional[List[str]] = None,
                             shard_rank: Optional[int] = None,
                             shard_size: Optional[int] = None,
                             batch_rows: int = 1024):
        """Stream one worker's shard as bounded-size pandas chunks
        without ever materializing the shard (the TPU-native equivalent
        of the reference's Petastorm batch readers,
        ref: spark/common/util.py:697, keras/remote.py:336)."""
        raise NotImplementedError

    def shard_num_rows(self, path: str, shard_rank: Optional[int] = None,
                       shard_size: Optional[int] = None) -> int:
        """Exact row count of one worker's shard, from metadata only."""
        raise NotImplementedError

    def dataset_fingerprint(self, df) -> Optional[str]:
        """Cheap content identity for materialization reuse; None means
        'unknown — always re-materialize'."""
        return None

    def matches_fingerprint(self, df, path: str) -> bool:
        return False

    # -- checkpoint helpers (pickle pytrees; ref: keras/remote.py
    # checkpoint callbacks write per-epoch files) ----------------------
    def save_checkpoint(self, run_id: str, obj: Any, epoch: Optional[int] = None):
        path = self.get_checkpoint_path(run_id)
        if epoch is not None:
            base, ext = os.path.splitext(path)
            self.write(f"{base}.epoch{epoch}{ext}", pickle.dumps(obj))
        self.write(path, pickle.dumps(obj))

    def load_checkpoint(self, run_id: str) -> Any:
        return pickle.loads(self.read(self.get_checkpoint_path(run_id)))

    def has_checkpoint(self, run_id: str) -> bool:
        return self.exists(self.get_checkpoint_path(run_id))

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """(ref: store.py:141-146 Store.create dispatches on URL
        scheme.)"""
        if prefix_path.startswith(("hdfs://", "gs://", "s3://")):
            raise ValueError(
                f"remote filesystem URL {prefix_path!r} is not natively "
                "supported: mount it (gcsfuse / hdfs-fuse) and pass the "
                "mounted path, the idiomatic arrangement on TPU-VMs"
            )
        return LocalStore(prefix_path, *args, **kwargs)


class LocalStore(Store):
    """Filesystem-backed store (ref: store.py LocalStore:148-260; the
    same path scheme: <prefix>/intermediate_train_data,
    <prefix>/runs/<run_id>/{checkpoint, logs})."""

    FS_PREFIX = "file://"

    def __init__(self, prefix_path: str, train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 runs_path: Optional[str] = None):
        if prefix_path.startswith(self.FS_PREFIX):
            prefix_path = prefix_path[len(self.FS_PREFIX):]
        self.prefix_path = os.path.abspath(prefix_path)
        self._train_path = train_path or os.path.join(
            self.prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            self.prefix_path, "intermediate_val_data")
        self._runs_path = runs_path or os.path.join(self.prefix_path, "runs")
        os.makedirs(self.prefix_path, exist_ok=True)

    # -- paths ---------------------------------------------------------
    def _idx(self, path: str, idx: Optional[int]) -> str:
        return path if idx is None else f"{path}.{idx}"

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        return self._idx(self._train_path, idx)

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        return self._idx(self._val_path, idx)

    def get_runs_path(self) -> str:
        return self._runs_path

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint.pkl")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    # -- IO ------------------------------------------------------------
    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{time.monotonic_ns()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic: readers never see partial files

    # -- parquet data path --------------------------------------------
    def is_parquet_dataset(self, path: str) -> bool:
        """(ref: store.py:167-175 — a directory of parquet part
        files, or a single parquet file.)"""
        if os.path.isfile(path):
            return path.endswith(".parquet")
        if not os.path.isdir(path):
            return False
        return any(
            n.endswith(".parquet") for n in os.listdir(path)
        ) or os.path.exists(os.path.join(path, "_SUCCESS"))

    def get_parquet_dataset(self, path: str):
        import pyarrow.parquet as pq

        return pq.ParquetDataset(path)

    def read_parquet(self, path: str, columns: Optional[List[str]] = None,
                     shard_rank: Optional[int] = None,
                     shard_size: Optional[int] = None):
        """One worker's view of the dataset as a pandas DataFrame.

        Column pruning always applies. When the dataset has at least
        `shard_size` part files, each rank reads only parts
        rank::size (the reference's Petastorm readers similarly shard
        by row group, common/util.py); otherwise the caller must
        row-slice the returned frame itself."""
        import pyarrow.parquet as pq

        parts = self._part_files(path)
        if (shard_rank is not None and shard_size is not None
                and shard_size > 1 and len(parts) >= shard_size):
            tables = [
                pq.read_table(p, columns=columns)
                for p in parts[shard_rank::shard_size]
            ]
            import pyarrow as pa

            return pa.concat_tables(tables).to_pandas()
        return pq.read_table(path, columns=columns).to_pandas()

    def sharding_by_parts(self, path: str, shard_size: int) -> bool:
        """True when read_parquet(shard_rank=..., shard_size=...) will
        return disjoint per-rank shards (enough part files)."""
        return shard_size > 1 and len(self._part_files(path)) >= shard_size

    def iter_parquet_batches(self, path: str,
                             columns: Optional[List[str]] = None,
                             shard_rank: Optional[int] = None,
                             shard_size: Optional[int] = None,
                             batch_rows: int = 1024):
        """Stream one worker's shard as pandas chunks of <= batch_rows
        rows, reading row-group-at-a-time so shards larger than RAM can
        train. With enough part files each rank streams only its own
        files; otherwise rows are strided rank::size by GLOBAL row
        index, so per-rank totals match `shard_num_rows` exactly (the
        estimator's collective step-count agreement depends on that)."""
        import pyarrow.parquet as pq

        parts = self._part_files(path)
        sharded = (shard_rank is not None and shard_size is not None
                   and shard_size > 1)
        by_parts = sharded and len(parts) >= shard_size
        files = parts[shard_rank::shard_size] if by_parts else parts
        offset = 0
        for f in files:
            pf = pq.ParquetFile(f)
            try:
                for rb in pf.iter_batches(batch_size=batch_rows,
                                          columns=columns):
                    pdf = rb.to_pandas()
                    if sharded and not by_parts:
                        first = (-(offset - shard_rank)) % shard_size
                        pdf = pdf.iloc[first::shard_size]
                    offset += len(rb)
                    if len(pdf):
                        yield pdf
            finally:
                pf.close()

    def shard_num_rows(self, path: str, shard_rank: Optional[int] = None,
                       shard_size: Optional[int] = None) -> int:
        """Exact per-shard row count from Parquet metadata (no data
        read), matching iter_parquet_batches' sharding."""
        import pyarrow.parquet as pq

        parts = self._part_files(path)
        sharded = (shard_rank is not None and shard_size is not None
                   and shard_size > 1)
        by_parts = sharded and len(parts) >= shard_size

        def rows(f):
            return pq.ParquetFile(f).metadata.num_rows

        if by_parts:
            return sum(rows(f) for f in parts[shard_rank::shard_size])
        total = sum(rows(f) for f in parts)
        if not sharded:
            return total
        return len(range(shard_rank, total, shard_size))

    def _part_files(self, path: str) -> List[str]:
        if os.path.isfile(path):
            return [path]
        if not os.path.isdir(path):
            return []
        return sorted(
            os.path.join(path, n) for n in os.listdir(path)
            if n.endswith(".parquet")
        )

    def save_data_frame(self, df, path: str):
        """Materialize a DataFrame (Spark or pandas) to store Parquet
        (ref: common/util.py prepare_data's
        df.write.parquet(train_data_path)). Writes a fingerprint marker
        so a later fit with different data re-materializes instead of
        silently training on stale rows."""
        fp = self.dataset_fingerprint(df)
        if hasattr(df, "write"):  # real pyspark DataFrame
            df.write.mode("overwrite").parquet(f"{self.FS_PREFIX}{path}")
        else:
            pdf = df.toPandas() if hasattr(df, "toPandas") else df
            if os.path.isdir(path):
                shutil.rmtree(path)
            os.makedirs(path, exist_ok=True)
            pdf.to_parquet(os.path.join(path, "part-00000.parquet"))
            # Spark-compatible completion marker.
            with open(os.path.join(path, "_SUCCESS"), "w"):
                pass
        if fp is not None:
            self.write(self._fingerprint_path(path), fp.encode())

    def _fingerprint_path(self, path: str) -> str:
        return f"{path}._fingerprint"

    def dataset_fingerprint(self, df) -> Optional[str]:
        if hasattr(df, "write"):
            # Spark DataFrames have no cheap content hash; None forces
            # re-materialization every fit (correct, if conservative).
            return None
        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        try:
            import pandas as pd

            h = pd.util.hash_pandas_object(pdf, index=False)
            return f"{len(pdf)}-{int(h.sum()) & 0xFFFFFFFFFFFFFFFF:x}"
        except Exception:
            return None

    def matches_fingerprint(self, df, path: str) -> bool:
        """True iff `path` holds a materialization of exactly `df`."""
        fp = self.dataset_fingerprint(df)
        if fp is None:
            return False
        mark = self._fingerprint_path(path)
        return self.exists(mark) and self.read(mark).decode() == fp


class HDFSStore(Store):
    """Placeholder matching the reference's class name
    (ref: store.py:263-433). Native HDFS clients are out of scope on
    TPU-VMs; use a FUSE mount + LocalStore."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "HDFSStore is not supported in horovod_tpu: mount HDFS "
            "(hdfs-fuse) and use LocalStore on the mounted path"
        )
