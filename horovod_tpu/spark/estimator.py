"""Estimator API over spark.run (ref: horovod/spark/keras/estimator.py,
horovod/spark/torch/estimator.py — fit framework models on DataFrames).

`JaxEstimator.fit(df)` trains a flax model data-parallel across Spark
tasks. With a `store` (ref: horovod/spark/common/store.py), the
DataFrame is materialized ONCE to store Parquet and every worker reads
its own shard from there — the reference's
DataFrame→Parquet→worker-reader pipeline (common/util.py prepare_data)
without shipping the dataset through the driver's pickled closure — and
rank 0 checkpoints params to the store per epoch, resuming from the
last checkpoint when fit() restarts. Without a store, partitions are
collected and shipped in the closure (small-data mode). Works with
pandas DataFrames directly for local use.
"""
from __future__ import annotations

import uuid
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from .store import Store


class JaxModel:
    """Fitted-model transformer (ref: spark estimators' Model)."""

    def __init__(self, model, params, feature_cols, label_col, output_col):
        self.model = model
        self.params = params
        self.feature_cols = feature_cols
        self.label_col = label_col
        self.output_col = output_col

    def transform(self, df):
        import pandas as pd

        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        x = np.stack([pdf[c].to_numpy() for c in self.feature_cols], axis=-1)
        out = np.asarray(self.model.apply(self.params, x))
        res = pdf.copy()
        res[self.output_col] = list(out)
        return res


class JaxEstimator:
    """(ref: estimator params subset — model, optimizer, loss, epochs,
    batch_size, feature/label cols, store/run_id for the checkpointing
    data path.)"""

    def __init__(
        self,
        model,
        optimizer,
        loss: Callable,
        feature_cols: Sequence[str],
        label_col: str,
        output_col: str = "prediction",
        num_proc: Optional[int] = None,
        epochs: int = 1,
        batch_size: int = 32,
        seed: int = 0,
        store: Optional[Store] = None,
        run_id: Optional[str] = None,
    ):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.output_col = output_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.store = store
        # Stable per-estimator run id so re-fitting resumes
        # (ref: estimator.py _has_checkpoint/run_id semantics).
        self.run_id = run_id or f"jax-estimator-{uuid.uuid4().hex[:8]}"

    # ------------------------------------------------------------------
    def _collect(self, df):
        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        x = np.stack(
            [pdf[c].to_numpy() for c in self.feature_cols], axis=-1
        ).astype(np.float32)
        y = pdf[self.label_col].to_numpy()
        return x, y

    def _prepare_data(self, df) -> str:
        """Materialize df into the store's train-data Parquet path
        unless an identical materialization already exists — identity is
        a content fingerprint, so fitting different data on the same
        store re-materializes instead of silently reusing stale rows
        (ref: common/util.py prepare_data's dataset keying)."""
        path = self.store.get_train_data_path()
        if not (self.store.is_parquet_dataset(path)
                and self.store.matches_fingerprint(df, path)):
            self.store.save_data_frame(df, path)
        return path

    def fit(self, df) -> JaxModel:
        est = self
        if self.store is not None:
            data_path = self._prepare_data(df)
            store = self.store
            run_id = self.run_id
            data_fp = store.dataset_fingerprint(df)
            x = y = None
        else:
            x, y = self._collect(df)
            store = None
            run_id = None
            data_path = None
            data_fp = None

        def train():
            import jax
            import optax

            import horovod_tpu as hvd

            hvd.init()
            if store is not None:
                # Worker-side STREAMING shard read from store Parquet —
                # the dataset never rides the pickled closure, only
                # needed columns are read row-group-at-a-time, so shards
                # larger than worker RAM train (the reference streams
                # through Petastorm readers for the same reason,
                # ref: spark/common/util.py:697, keras/remote.py:336).
                cols = est.feature_cols + [est.label_col]
                n_rows_local = store.shard_num_rows(
                    data_path, hvd.rank(), hvd.size())

                def _to_arrays(pdf):
                    bx = np.stack(
                        [pdf[c].to_numpy() for c in est.feature_cols],
                        axis=-1,
                    ).astype(np.float32)
                    return bx, pdf[est.label_col].to_numpy()

                def epoch_batches(epoch):
                    """Exactly-batch_size batches with a buffer-local
                    shuffle (the streaming analogue of the reference
                    readers' shuffling_queue_capacity); memory is
                    bounded by ~5x batch_size rows."""
                    rng = np.random.RandomState(epoch)
                    bufs = []
                    have = 0
                    for pdf in store.iter_parquet_batches(
                            data_path, columns=cols,
                            shard_rank=hvd.rank(), shard_size=hvd.size(),
                            batch_rows=max(est.batch_size * 4, 1024)):
                        bx, by = _to_arrays(pdf)
                        perm = rng.permutation(len(by))
                        bufs.append((bx[perm], by[perm]))
                        have += len(by)
                        while have >= est.batch_size:
                            X = np.concatenate([b for b, _ in bufs])
                            Y = np.concatenate([b for _, b in bufs])
                            yield (X[:est.batch_size], Y[:est.batch_size])
                            bufs = [(X[est.batch_size:],
                                     Y[est.batch_size:])]
                            have -= est.batch_size
                    if have:
                        # Final partial batch, so a shard smaller than
                        # batch_size still trains (matches the ragged
                        # last-step semantics of the in-memory path).
                        yield (np.concatenate([b for b, _ in bufs]),
                               np.concatenate([b for _, b in bufs]))

                example_x = None
                if n_rows_local:
                    example_x = next(epoch_batches(0))[0]
            else:
                xs = x[hvd.rank()::hvd.size()]
                ys = y[hvd.rank()::hvd.size()]
                n_rows_local = len(xs)
                example_x = xs[: est.batch_size] if len(xs) else None

            start_epoch = 0
            saved_opt = None
            params = None
            # Resume is decided on rank 0 ONLY and broadcast: on a
            # store whose files aren't identically visible everywhere
            # (LocalStore without a shared mount), per-rank checkpoint
            # probing would give ranks different start epochs — a
            # collective-count mismatch (hang) or silent optimizer
            # divergence.
            if hvd.rank() == 0 and store is not None \
                    and store.has_checkpoint(run_id):
                ckpt = store.load_checkpoint(run_id)
                # A checkpoint is only a valid resume point for the SAME
                # dataset: a differing fingerprint means the caller
                # re-fit with new data, so training must restart instead
                # of silently returning the old params.
                ck_fp = ckpt.get("data_fp")
                if data_fp is None or ck_fp == data_fp:
                    params = ckpt["params"]
                    start_epoch = int(ckpt.get("epoch", -1)) + 1
                    saved_opt = ckpt.get("opt_state")
            if hvd.size() > 1:
                start_epoch, params, saved_opt = hvd.broadcast_object(
                    (start_epoch, params, saved_opt), root_rank=0,
                    name="estimator_resume")
            if params is None:
                # Init on the lowest rank that has any rows, then object-
                # broadcast: raising on only the empty-shard ranks would
                # leave the others hanging in the next collective, and
                # the all-empty verdict must be agreed so every rank
                # raises together.
                can_init = example_x is not None
                root = 0
                if hvd.size() > 1:
                    have = hvd.allgather_object(can_init,
                                                name="estimator_can_init")
                    if not any(have):
                        raise ValueError(
                            "cannot initialize model: every rank's shard "
                            "is empty and no checkpoint exists"
                        )
                    root = have.index(True)
                elif not can_init:
                    raise ValueError(
                        "cannot initialize model: the dataset is empty "
                        "and no checkpoint exists"
                    )
                if hvd.rank() == root:
                    params = est.model.init(
                        jax.random.PRNGKey(est.seed), example_x
                    )
                if hvd.size() > 1:
                    params = hvd.broadcast_object(
                        params, root_rank=root, name="estimator_init")
            # No broadcast on the else path: checkpoint params already
            # arrived on every rank via the resume broadcast_object
            # above — a second full-size broadcast would double resume
            # startup traffic for no effect.
            tx = hvd.DistributedOptimizer(est.optimizer)
            opt_state = saved_opt if saved_opt is not None else tx.init(params)

            grad_fn = jax.jit(jax.value_and_grad(
                lambda p, bx, by: est.loss(est.model.apply(p, bx), by)
            ))
            # Per-epoch step count must be identical on every rank —
            # each step's grad allreduce is a collective, and shards can
            # be ragged. Agree on the minimum shard length (exact, from
            # Parquet metadata on the store path).
            n_agreed = n_rows_local
            if hvd.size() > 1:
                n_agreed = min(hvd.allgather_object(n_rows_local))
            # Agreed-empty shard → zero steps everywhere (no rank may
            # break out of the loop alone; each step is a collective).
            steps = 0 if n_agreed == 0 else max(n_agreed // est.batch_size, 1)

            def batches_for(epoch):
                if store is not None:
                    return epoch_batches(epoch)
                perm = np.random.RandomState(epoch).permutation(len(xs))
                return (
                    (xs[perm[i * est.batch_size:(i + 1) * est.batch_size]],
                     ys[perm[i * est.batch_size:(i + 1) * est.batch_size]])
                    for i in range(max(steps, 1))
                )

            for epoch in range(start_epoch, est.epochs):
                it = batches_for(epoch)
                for _ in range(steps):
                    bx, by = next(it)
                    _, grads = grad_fn(params, bx, by)
                    upd, opt_state = tx.update(grads, opt_state, params)
                    params = optax.apply_updates(params, upd)
                if store is not None and hvd.rank() == 0:
                    # Per-epoch checkpoint to the store, rank 0 only
                    # (ref: keras/remote.py checkpoint callback; §5.4
                    # only-rank-0-writes convention).
                    store.save_checkpoint(run_id, {
                        "params": jax.tree.map(np.asarray, params),
                        "opt_state": jax.tree.map(np.asarray, opt_state),
                        "epoch": epoch,
                        "data_fp": data_fp,
                    }, epoch=epoch)
            return jax.tree.map(np.asarray, params)

        num_proc = self.num_proc or 1
        if hasattr(df, "rdd") or num_proc > 1:
            results = self._run_distributed(train, num_proc, df)
        else:
            results = [train()]
        return JaxModel(self.model, results[0], self.feature_cols,
                        self.label_col, self.output_col)

    def _run_distributed(self, train, num_proc, df):
        if hasattr(df, "rdd"):
            from .runner import run as spark_run

            return spark_run(train, num_proc=num_proc)
        from ..runner import run as local_run

        return local_run(train, np=num_proc)
